"""Elementwise & scalar math ops. Reference: python/paddle/tensor/math.py / ops.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..tensor import Tensor
from . import apply_op, binary_op, unary_op

__all__ = [
    "trace", "take", "vander", "sigmoid", "numel", "is_floating_point",
    "is_integer", "is_complex",
    # unary
    "abs", "acos", "acosh", "asin", "asinh", "atan", "atanh", "ceil", "cos", "cosh",
    "deg2rad", "rad2deg", "digamma", "erf", "erfinv", "exp", "expm1", "floor", "frac",
    "i0", "lgamma", "log", "log10", "log1p", "log2", "logit", "neg", "reciprocal",
    "round", "rsqrt", "sign", "sgn", "sin", "sinh", "sqrt", "square", "tan", "tanh",
    "trunc", "angle", "conj", "real", "imag", "isfinite", "isinf", "isnan", "nan_to_num",
    # binary
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder", "pow",
    "maximum", "minimum", "fmax", "fmin", "atan2", "logaddexp", "heaviside", "hypot",
    "nextafter", "copysign", "gcd", "lcm", "ldexp", "inner", "outer", "kron", "lerp",
    "trapezoid", "cumulative_trapezoid", "diff",
    # scalar-ish / misc
    "scale", "clip", "stanh", "multiplex", "addmm",
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp",
    "isclose", "allclose", "equal_all",
    "increment", "count_nonzero", "broadcast_shape",
]

# ------------------------------------------------------------------ unary family
abs = unary_op(jnp.abs, "abs")
acos = unary_op(jnp.arccos, "acos")
acosh = unary_op(jnp.arccosh, "acosh")
asin = unary_op(jnp.arcsin, "asin")
asinh = unary_op(jnp.arcsinh, "asinh")
atan = unary_op(jnp.arctan, "atan")
atanh = unary_op(jnp.arctanh, "atanh")
ceil = unary_op(jnp.ceil, "ceil")
cos = unary_op(jnp.cos, "cos")
cosh = unary_op(jnp.cosh, "cosh")
deg2rad = unary_op(jnp.deg2rad, "deg2rad")
rad2deg = unary_op(jnp.rad2deg, "rad2deg")
digamma = unary_op(jax.scipy.special.digamma, "digamma")
erf = unary_op(jax.scipy.special.erf, "erf")
erfinv = unary_op(jax.scipy.special.erfinv, "erfinv")
exp = unary_op(jnp.exp, "exp")
expm1 = unary_op(jnp.expm1, "expm1")
floor = unary_op(jnp.floor, "floor")
i0 = unary_op(jnp.i0, "i0")
lgamma = unary_op(jax.scipy.special.gammaln, "lgamma")
log = unary_op(jnp.log, "log")
log10 = unary_op(jnp.log10, "log10")
log1p = unary_op(jnp.log1p, "log1p")
log2 = unary_op(jnp.log2, "log2")
neg = unary_op(jnp.negative, "neg")
reciprocal = unary_op(jnp.reciprocal, "reciprocal")
round = unary_op(jnp.round, "round")
rsqrt = unary_op(jax.lax.rsqrt, "rsqrt")
sign = unary_op(jnp.sign, "sign")
sgn = unary_op(jnp.sign, "sgn")
sin = unary_op(jnp.sin, "sin")
sinh = unary_op(jnp.sinh, "sinh")
sqrt = unary_op(jnp.sqrt, "sqrt")
square = unary_op(jnp.square, "square")
tan = unary_op(jnp.tan, "tan")
tanh = unary_op(jnp.tanh, "tanh")
trunc = unary_op(jnp.trunc, "trunc")
angle = unary_op(jnp.angle, "angle")
conj = unary_op(jnp.conj, "conj")
real = unary_op(jnp.real, "real")
imag = unary_op(jnp.imag, "imag")
isfinite = unary_op(jnp.isfinite, "isfinite")
isinf = unary_op(jnp.isinf, "isinf")
isnan = unary_op(jnp.isnan, "isnan")


def frac(x, name=None):
    return apply_op(lambda v: v - jnp.trunc(v), "frac", x)


def logit(x, eps=None, name=None):
    def f(v):
        u = v if eps is None else jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(u / (1.0 - u))

    return apply_op(f, "logit", x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(
        lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), "nan_to_num", x
    )


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda v: scale_b * jnp.tanh(scale_a * v), "stanh", x)


# ------------------------------------------------------------------ binary family
def _promote_binop(jfn, name):
    """Binary op with paddle-ish type promotion: Tensor op python-scalar keeps tensor
    dtype for ints, promotes int tensor + float scalar to default float."""

    def op(x, y, name=None):
        def f(a, b):
            a_t = isinstance(x, Tensor)
            b_t = isinstance(y, Tensor)
            if a_t and not b_t and isinstance(y, (int, float, bool)) and not isinstance(y, bool):
                if isinstance(y, float) and jnp.issubdtype(jnp.asarray(a).dtype, jnp.integer):
                    a = a.astype(_dt.get_default_dtype())
                else:
                    b = jnp.asarray(y, dtype=jnp.asarray(a).dtype) if not isinstance(y, float) else b
            if b_t and not a_t and isinstance(x, (int, float, bool)) and not isinstance(x, bool):
                if isinstance(x, float) and jnp.issubdtype(jnp.asarray(b).dtype, jnp.integer):
                    b = b.astype(_dt.get_default_dtype())
                elif not isinstance(x, float):
                    a = jnp.asarray(x, dtype=jnp.asarray(b).dtype)
            return jfn(a, b)

        return apply_op(f, op.__name__, x, y)

    op.__name__ = name
    op.__qualname__ = name
    return op


add = _promote_binop(jnp.add, "add")
subtract = _promote_binop(jnp.subtract, "subtract")
multiply = _promote_binop(jnp.multiply, "multiply")
mod = _promote_binop(jnp.mod, "mod")
remainder = mod
maximum = _promote_binop(jnp.maximum, "maximum")
minimum = _promote_binop(jnp.minimum, "minimum")
fmax = _promote_binop(jnp.fmax, "fmax")
fmin = _promote_binop(jnp.fmin, "fmin")
atan2 = _promote_binop(jnp.arctan2, "atan2")
logaddexp = _promote_binop(jnp.logaddexp, "logaddexp")
heaviside = _promote_binop(jnp.heaviside, "heaviside")
hypot = _promote_binop(jnp.hypot, "hypot")
nextafter = _promote_binop(jnp.nextafter, "nextafter")
copysign = _promote_binop(jnp.copysign, "copysign")
gcd = binary_op(jnp.gcd, "gcd")
lcm = binary_op(jnp.lcm, "lcm")
ldexp = binary_op(jnp.ldexp, "ldexp")


def divide(x, y, name=None):
    """paddle.divide — true division; int/int promotes to float (paddle semantics)."""

    def f(a, b):
        a, b = jnp.asarray(a), jnp.asarray(b)
        if jnp.issubdtype(a.dtype, jnp.integer) and jnp.issubdtype(b.dtype, jnp.integer):
            a = a.astype(_dt.get_default_dtype())
            b = b.astype(_dt.get_default_dtype())
        return jnp.true_divide(a, b)

    return apply_op(f, "divide", x, y)


def floor_divide(x, y, name=None):
    return apply_op(lambda a, b: jnp.floor_divide(a, b), "floor_divide", x, y)


def pow(x, y, name=None):
    def f(a, b):
        return jnp.power(a, b)

    return apply_op(f, "pow", x, y)


def inner(x, y, name=None):
    return apply_op(jnp.inner, "inner", x, y)


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), "outer", x, y)


def kron(x, y, name=None):
    return apply_op(jnp.kron, "kron", x, y)


def lerp(x, y, weight, name=None):
    return apply_op(lambda a, b, w: a + w * (b - a), "lerp", x, y, weight)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def f(yv, xv):
        return jnp.trapezoid(yv, x=xv, dx=1.0 if dx is None else dx, axis=axis)

    return apply_op(f, "trapezoid", y, x)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def f(yv, xv):
        d = dx if dx is None or xv is None else None
        import jax.scipy.integrate as _int  # noqa

        # manual: 0.5*(y[i]+y[i+1])*dx cumulative
        yv = jnp.moveaxis(yv, axis, -1)
        if xv is not None:
            xv = jnp.moveaxis(xv, axis, -1) if xv.ndim > 1 else xv
            dxs = jnp.diff(xv, axis=-1)
        else:
            dxs = d if d is not None else 1.0
        avg = 0.5 * (yv[..., 1:] + yv[..., :-1]) * dxs
        return jnp.moveaxis(jnp.cumsum(avg, axis=-1), -1, axis)

    return apply_op(f, "cumulative_trapezoid", y, x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return apply_op(
        lambda v, p, a: jnp.diff(v, n=n, axis=axis, prepend=p, append=a),
        "diff", x, prepend, append,
    )


# ------------------------------------------------------------------ misc
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = scale, bias

    def f(v, sv):
        sv2 = sv if sv is not None else s
        out = v * sv2 + b if bias_after_scale else (v + b) * sv2
        return out.astype(v.dtype) if not jnp.issubdtype(v.dtype, jnp.floating) else out

    st = s if isinstance(s, Tensor) else None
    out = apply_op(f, "scale", x, st)
    return out


def clip(x, min=None, max=None, name=None):
    def f(v, lo, hi):
        return jnp.clip(v, lo, hi)

    return apply_op(f, "clip", x, min, max)


def multiplex(inputs, index, name=None):
    def f(idx, *ins):
        stacked = jnp.stack(ins, axis=0)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1).astype(jnp.int32), rows]

    return apply_op(f, "multiplex", index, *inputs)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(
        lambda i, a, b: beta * i + alpha * (a @ b), "addmm", input, x, y
    )


def cumsum(x, axis=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda v: jnp.cumsum(v, axis=axis, dtype=d), "cumsum", x)


def cumprod(x, dim=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda v: jnp.cumprod(v, axis=dim, dtype=d), "cumprod", x)


def cummax(x, axis=None, dtype="int64", name=None):
    def f(v):
        ax = axis
        if ax is None:
            v = v.reshape(-1)
            ax = 0
        vals = jax.lax.associative_scan(jnp.maximum, v, axis=ax)
        # indices: position of current running max
        n = v.shape[ax]
        ar = jnp.arange(n).reshape([-1 if i == ax % v.ndim else 1 for i in range(v.ndim)])
        eq = v == vals
        run_idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, ar, -1), axis=ax)
        return vals, run_idx.astype(_dt.convert_dtype(dtype))

    return apply_op(f, "cummax", x)


def cummin(x, axis=None, dtype="int64", name=None):
    def f(v):
        ax = axis
        if ax is None:
            v = v.reshape(-1)
            ax = 0
        vals = jax.lax.associative_scan(jnp.minimum, v, axis=ax)
        n = v.shape[ax]
        ar = jnp.arange(n).reshape([-1 if i == ax % v.ndim else 1 for i in range(v.ndim)])
        eq = v == vals
        run_idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, ar, -1), axis=ax)
        return vals, run_idx.astype(_dt.convert_dtype(dtype))

    return apply_op(f, "cummin", x)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    # numerically-stable: global max subtraction before the scan
    def g(v):
        ax = 0 if axis is None else axis
        vv = v.reshape(-1) if axis is None else v
        m = jnp.max(vv, axis=ax, keepdims=True)
        return m + jnp.log(jnp.cumsum(jnp.exp(vv - m), axis=ax))

    return apply_op(g, "logcumsumexp", x)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        "isclose", x, y,
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        "allclose", x, y,
    )


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), "equal_all", x, y)


def increment(x, value=1.0, name=None):
    x._value = x._value + jnp.asarray(value, x._value.dtype)
    return x


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.count_nonzero(v, axis=axis, keepdims=keepdim).astype(_dt.int64),
        "count_nonzero", x,
    )


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    """Reference: python/paddle/tensor/math.py trace."""
    return apply_op(
        lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
        "trace", x)


def take(x, index, mode="raise", name=None):
    """Reference: python/paddle/tensor/math.py take — flat-index gather with
    clip/wrap out-of-range modes."""
    jmode = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return apply_op(
        lambda v, i: jnp.take(v.reshape(-1), i, mode=jmode), "take", x, index)


def vander(x, n=None, increasing=False, name=None):
    """Reference: python/paddle/tensor/math.py vander."""
    def f(v):
        cols = v.shape[0] if n is None else n
        powers = jnp.arange(cols)
        if not increasing:
            powers = powers[::-1]
        return v[:, None] ** powers[None, :]

    return apply_op(f, "vander", x)


def sigmoid(x, name=None):
    return apply_op(jax.nn.sigmoid, "sigmoid", x)


def numel(x, name=None):
    from ..tensor import Tensor as _T

    return _T(jnp.asarray(int(np.prod(x.shape)) if x.ndim else 1))


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x._value if hasattr(x, "_value") else x).dtype,
                          jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x._value if hasattr(x, "_value") else x).dtype,
                          jnp.integer)


def is_complex(x):
    return jnp.issubdtype(jnp.asarray(x._value if hasattr(x, "_value") else x).dtype,
                          jnp.complexfloating)
