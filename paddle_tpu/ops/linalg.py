"""Linear algebra ops. Reference: python/paddle/tensor/linalg.py.

matmul is THE MXU op — everything here lowers to XLA dot_general so the TPU systolic array
gets large fused contractions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..tensor import Tensor
from . import apply_op

__all__ = [
    "matmul", "mm", "bmm", "dot", "t", "norm", "dist", "cross", "cholesky",
    "cholesky_solve", "bincount", "mv", "histogram", "histogramdd", "matrix_power", "qr",
    "lu", "eig", "eigh", "eigvals", "eigvalsh", "svd", "pinv", "solve",
    "triangular_solve", "lstsq", "slogdet", "det", "inverse", "matrix_rank", "cov",
    "corrcoef", "cond", "vecdot", "multi_dot", "householder_product", "matrix_exp",
    "matrix_norm", "vector_norm", "cholesky_inverse", "diagonal",
    "matrix_transpose", "svdvals", "lu_unpack", "ormqr", "svd_lowrank",
    "pca_lowrank", "fp8_fp8_half_gemm_fused",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return apply_op(f, "matmul", x, y)


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, "bmm", x, y)


def dot(x, y, name=None):
    def f(a, b):
        return jnp.sum(a * b, axis=-1)

    return apply_op(f, "dot", x, y)


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, "mv", x, vec)


def t(input, name=None):
    return apply_op(lambda v: v.T if v.ndim <= 2 else jnp.swapaxes(v, -1, -2), "t", input)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(v):
        pp = p
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if pp is None:
            pp = "fro" if (ax is None or isinstance(ax, tuple)) else 2
        if ax is None and pp in ("fro", 2):
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(v))))
        if pp == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(v)), axis=ax, keepdims=keepdim))
        if pp == "nuc":
            s = jnp.linalg.svd(v, compute_uv=False)
            return jnp.sum(s, axis=-1, keepdims=keepdim)
        if pp == np.inf or pp == float("inf"):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp == -np.inf or pp == float("-inf"):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(v), pp), axis=ax, keepdims=keepdim), 1.0 / pp
        )

    return apply_op(f, "norm", x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """Reference: python/paddle/tensor/linalg.py vector_norm — vector p-norm;
    axis=None flattens ALL dims (unlike norm's fro default)."""
    def f(v):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if ax is None:
            ax = tuple(range(v.ndim))
        a = jnp.abs(v)
        if p == float("inf"):
            return jnp.max(a, axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(a, axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        if p == 2:
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        return jnp.power(jnp.sum(jnp.power(a, p), axis=ax, keepdims=keepdim),
                         1.0 / p)

    return apply_op(f, "vector_norm", x)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """Reference: python/paddle/tensor/linalg.py matrix_norm — norm over a
    2-axis slice: 'fro', 'nuc', +-1 (col sums), +-inf (row sums), +-2
    (extreme singular values)."""
    if not (isinstance(axis, (list, tuple)) and len(axis) == 2):
        raise ValueError(f"matrix_norm axis must be 2 axes, got {axis!r}")

    def f(v):
        ax = tuple(int(a) % v.ndim for a in axis)
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(v)), axis=ax,
                                    keepdims=keepdim))
        # move the matrix axes last for svd/sum-based definitions
        rest = [d for d in range(v.ndim) if d not in ax]
        vm = jnp.transpose(v, rest + list(ax))
        if p == "nuc" or p in (2, -2):
            s = jnp.linalg.svd(vm, compute_uv=False)
            r = (jnp.sum(s, axis=-1) if p == "nuc"
                 else (jnp.max(s, axis=-1) if p == 2 else jnp.min(s, axis=-1)))
        elif p in (1, -1, float("inf"), float("-inf")):
            # p=1: max col-sum; p=inf: max row-sum (negatives take min)
            sum_ax = -2 if p in (1, -1) else -1
            sums = jnp.sum(jnp.abs(vm), axis=sum_ax)
            r = jnp.max(sums, axis=-1) if p in (1, float("inf")) \
                else jnp.min(sums, axis=-1)
        else:
            raise ValueError(f"matrix_norm: unsupported p={p!r}")
        if keepdim:
            for a in sorted(ax):
                r = jnp.expand_dims(r, a)
        return r

    return apply_op(f, "matrix_norm", x)


def vecdot(x, y, axis=-1, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=axis), "vecdot", x, y)


def dist(x, y, p=2, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype)).astype(a.dtype)
        if p == float("inf"):
            return jnp.max(d)
        if p == float("-inf"):
            return jnp.min(d)
        return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)

    return apply_op(f, "dist", x, y)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next((i for i, s in enumerate(a.shape) if s == 3), -1)
        return jnp.cross(a, b, axis=ax)

    return apply_op(f, "cross", x, y)


def cholesky(x, upper=False, name=None):
    def f(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return apply_op(f, "cholesky", x)


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)

    return apply_op(f, "cholesky_solve", x, y)


def bincount(x, weights=None, minlength=0, name=None):
    v = np.asarray(x._value)
    w = np.asarray(weights._value) if isinstance(weights, Tensor) else weights
    return Tensor(jnp.asarray(np.bincount(v, weights=w, minlength=minlength)))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    v = np.asarray(input._value)
    rng = None if (min == 0 and max == 0) else (min, max)
    w = np.asarray(weight._value) if isinstance(weight, Tensor) else weight
    h, _ = np.histogram(v, bins=bins, range=rng, weights=w, density=density)
    return Tensor(jnp.asarray(h if density else h.astype(np.int64)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    v = np.asarray(x._value)
    w = np.asarray(weights._value) if isinstance(weights, Tensor) else weights
    h, edges = np.histogramdd(v, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(h)), [Tensor(jnp.asarray(e)) for e in edges]


def matrix_power(x, n, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_power(v, n), "matrix_power", x)


def matrix_exp(x, name=None):
    return apply_op(jax.scipy.linalg.expm, "matrix_exp", x)


def qr(x, mode="reduced", name=None):
    return apply_op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)) if mode != "r"
                    else (jnp.linalg.qr(v, mode="r"),), "qr", x) if mode == "r" else \
        apply_op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), "qr", x)


def lu(x, pivot=True, get_infos=False, name=None):
    def f(v):
        factor_dtype = v.dtype
        if v.dtype == jnp.float64:
            try:
                on_tpu = jax.default_backend() == "tpu"
            except Exception:
                on_tpu = False
            if on_tpu:
                # TPU's LuDecomposition expander implements only F32/C64;
                # factor in f32 and cast back (documented precision boundary)
                factor_dtype = jnp.float32
        lu_, piv = jax.scipy.linalg.lu_factor(v.astype(factor_dtype))
        lu_ = lu_.astype(v.dtype)
        if get_infos:
            return lu_, piv.astype(_dt.int32) + 1, jnp.zeros((), _dt.int32)
        return lu_, piv.astype(_dt.int32) + 1

    return apply_op(f, "lu", x)


def eig(x, name=None):
    v = np.asarray(x._value)
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda v: tuple(jnp.linalg.eigh(v, symmetrize_input=True)), "eigh", x)


def eigvals(x, name=None):
    v = np.asarray(x._value)
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda v: jnp.linalg.eigvalsh(v), "eigvalsh", x)


def svd(x, full_matrices=False, name=None):
    return apply_op(
        lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), "svd", x
    )


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), "pinv", x)


def solve(x, y, name=None):
    def f(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)

    return apply_op(f, "solve", x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply_op(f, "triangular_solve", x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank.astype(_dt.int64), sv

    return apply_op(f, "lstsq", x, y)


def slogdet(x, name=None):
    def f(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return apply_op(f, "slogdet", x)


def det(x, name=None):
    return apply_op(jnp.linalg.det, "det", x)


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, "inverse", x)


def matrix_rank(x, tol=None, hermitian=False, atol=None, rtol=None, name=None):
    def f(v):
        return jnp.linalg.matrix_rank(v, rtol=tol if tol is not None else rtol).astype(_dt.int64)

    return apply_op(f, "matrix_rank", x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def f(v, fw, aw):
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fw, aweights=aw)

    return apply_op(f, "cov", x, fweights, aweights)


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), "corrcoef", x)


def cond(x, p=None, name=None):
    def f(v):
        return jnp.linalg.cond(v, p=p)

    return apply_op(f, "cond", x)


def multi_dot(x, name=None):
    return apply_op(lambda *vs: jnp.linalg.multi_dot(vs), "multi_dot", *list(x))


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)

        def apply_single(acc, i):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i].at[..., i].set(1.0))
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) == i, 1.0, jnp.where(jnp.arange(m) < i, 0.0, v))
            H = eye - t[..., i] * jnp.outer(v, v)
            return acc @ H, None

        Q = eye
        for i in range(n):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) == i, 1.0, jnp.where(jnp.arange(m) < i, 0.0, v))
            H = eye - t[..., i] * jnp.outer(v, v)
            Q = Q @ H
        return Q[..., :, :n]

    return apply_op(f, "householder_product", x, tau)


def cholesky_inverse(x, upper=False, name=None):
    """Reference: tensor/linalg.py cholesky_inverse — inverse of A from its
    Cholesky factor: A = L L^T (lower) or U^T U (upper), so
    A^-1 = L^-T L^-1 (resp. U^-1 U^-T)."""
    def f(u):
        eye = jnp.eye(u.shape[-1], dtype=u.dtype)
        linv = jax.scipy.linalg.solve_triangular(u, eye, lower=not upper)
        return linv.T @ linv if not upper else linv @ linv.T

    return apply_op(f, "cholesky_inverse", x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
        "diagonal", x)


def matrix_transpose(x, name=None):
    return apply_op(lambda v: jnp.swapaxes(v, -2, -1), "matrix_transpose", x)


def svdvals(x, name=None):
    return apply_op(lambda v: jnp.linalg.svd(v, compute_uv=False), "svdvals", x)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Reference: tensor/linalg.py lu_unpack — split packed LU into P, L, U."""
    def f(a, piv):
        m, n = a.shape[-2], a.shape[-1]
        k = min(m, n)
        L = jnp.tril(a[..., :, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        U = jnp.triu(a[..., :k, :])
        # pivots (1-based sequential swaps) -> permutation matrix
        perm = jnp.arange(m)
        piv0 = piv.astype(jnp.int32) - 1

        def body(i, p):
            j = piv0[..., i]
            pi, pj = p[i], p[j]
            p = p.at[i].set(pj)
            return p.at[j].set(pi)

        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        P = jnp.eye(m, dtype=a.dtype)[perm].T
        return P, L, U

    return apply_op(f, "lu_unpack", lu_data, lu_pivots, nout=3)


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Reference: tensor/linalg.py ormqr — multiply y by the FULL m x m
    orthogonal Q implied by the householder factors (x, tau), without forming
    Q: reflectors H_i = I - tau_i v_i v_i^T apply directly to y (left: in
    reverse order for Q @ y, forward for Q^T @ y; right mirrors)."""

    def f(a, t, yv):
        m = a.shape[-2]
        k = t.shape[-1]

        def reflector(i):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i].at[..., i].set(1.0))
            return v

        order = range(k) if (left and transpose) or (not left and not transpose) \
            else range(k - 1, -1, -1)
        out = yv
        for i in order:
            v = reflector(i)
            if left:
                # H out = out - tau v (v^T out)
                out = out - t[..., i] * jnp.outer(v, v @ out)
            else:
                out = out - t[..., i] * jnp.outer(out @ v, v)
        return out

    return apply_op(f, "ormqr", x, tau, y)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Reference: tensor/linalg.py svd_lowrank — randomized range finder +
    SVD on the small projected matrix (Halko et al.), TPU-friendly: q-rank
    matmuls + one small SVD."""
    def f(a, key_seed=0):
        m, n = a.shape[-2], a.shape[-1]
        rank = min(q, m, n)
        key = jax.random.key(0)
        omega = jax.random.normal(key, a.shape[:-2] + (n, rank), a.dtype)
        Y = a @ omega
        for _ in range(niter):
            Y = a @ (jnp.swapaxes(a, -2, -1) @ Y)
        Q, _ = jnp.linalg.qr(Y)
        B = jnp.swapaxes(Q, -2, -1) @ a
        u_b, s, vh = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u_b, s, jnp.swapaxes(vh, -2, -1)

    if M is not None:
        return apply_op(lambda a, mm: f(a - mm), "svd_lowrank", x, M, nout=3)
    return apply_op(f, "svd_lowrank", x, nout=3)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Reference: tensor/linalg.py pca_lowrank — center, then delegate to the
    same randomized range-finder as svd_lowrank (one Halko implementation)."""
    if center:
        from .reduction import mean as _mean

        x = x - _mean(x, axis=-2, keepdim=True)
    return svd_lowrank(x, q=q or 6, niter=niter)


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, output_dtype="float16",
                            activation=None, name=None):
    """Reference: incubate fp8 cutlass gemm (sm89+). TPU v5e has no fp8
    MXU mode exposed through XLA; computes in bf16 (the TPU half type) with
    the same call signature — documented precision divergence, not a stub."""
    def f(a, b, bb):
        a = jnp.swapaxes(a, -2, -1) if transpose_x else a
        b = jnp.swapaxes(b, -2, -1) if transpose_y else b
        out = a.astype(jnp.bfloat16) @ b.astype(jnp.bfloat16)
        if bb is not None:
            out = out + bb.astype(out.dtype)
        if activation in ("gelu",):
            out = jax.nn.gelu(out)
        elif activation in ("relu",):
            out = jax.nn.relu(out)
        return out.astype(jnp.bfloat16 if output_dtype in ("bfloat16",)
                          else jnp.float16)

    return apply_op(f, "fp8_fp8_half_gemm_fused", x, y, bias)
