"""Creation ops. Reference: python/paddle/tensor/creation.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..tensor import Tensor, to_tensor  # noqa: F401 (re-exported)
from . import apply_op

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "diag",
    "diagflat",
    "tril",
    "triu",
    "meshgrid",
    "assign",
    "clone",
    "complex",
    "tril_indices",
    "triu_indices",
    "one_hot",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._value)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
    return Tensor(jnp.zeros(_shape_list(shape), dtype))


def ones(shape, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
    return Tensor(jnp.ones(_shape_list(shape), dtype))


def full(shape, fill_value, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype)
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = np.dtype(bool)
        elif isinstance(fill_value, int):
            dtype = _dt.get_default_dtype()  # paddle full defaults to float
        else:
            dtype = _dt.get_default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value, dtype))


def zeros_like(x, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype)
    return Tensor(jnp.zeros_like(x._value if isinstance(x, Tensor) else x, dtype))


def ones_like(x, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype)
    return Tensor(jnp.ones_like(x._value if isinstance(x, Tensor) else x, dtype))


def full_like(x, fill_value, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype)
    return Tensor(jnp.full_like(x._value if isinstance(x, Tensor) else x, fill_value, dtype))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    """paddle.arange — int64 default for int args, float for float args."""
    if end is None:
        start, end = 0, start
    s = start.item() if isinstance(start, Tensor) else start
    e = end.item() if isinstance(end, Tensor) else end
    st = step.item() if isinstance(step, Tensor) else step
    dtype = _dt.convert_dtype(dtype)
    if dtype is None:
        if any(isinstance(v, float) for v in (s, e, st)):
            dtype = _dt.get_default_dtype()
        else:
            dtype = _dt.int64
    return Tensor(jnp.arange(s, e, st, dtype=dtype))


def linspace(start, stop, num, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
    s = start.item() if isinstance(start, Tensor) else start
    e = stop.item() if isinstance(stop, Tensor) else stop
    n = num.item() if isinstance(num, Tensor) else num
    return Tensor(jnp.linspace(s, e, int(n), dtype=dtype))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=float(base), dtype=dtype))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns), dtype=dtype))


def diag(x, offset=0, padding_value=0, name=None):
    if padding_value != 0 and getattr(x, "ndim", 1) == 1:
        def g(v):
            n = v.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, v.dtype)
            idx = jnp.arange(v.shape[0])
            r = idx if offset >= 0 else idx - offset
            c = idx + offset if offset >= 0 else idx
            return out.at[r, c].set(v)

        return apply_op(g, "diag", x)
    return apply_op(lambda v: jnp.diag(v, k=offset), "diag", x)


def diagflat(x, offset=0, name=None):
    return apply_op(lambda v: jnp.diagflat(v, k=offset), "diagflat", x)


def tril(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.tril(v, k=diagonal), "tril", x)


def triu(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.triu(v, k=diagonal), "triu", x)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt.convert_dtype(dtype)))


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[t._value if isinstance(t, Tensor) else t for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    src = x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if output is not None:
        output._value = src
        return output
    return apply_op(lambda v: v + jnp.zeros((), v.dtype), "assign", x) if isinstance(x, Tensor) else Tensor(src)


def clone(x, name=None):
    return x.clone()


def complex(real, imag, name=None):
    return apply_op(lambda r, i: r + 1j * i, "complex", real, imag)


def one_hot(x, num_classes, name=None):
    from ..framework import dtype as _d

    return apply_op(
        lambda v: jnp.eye(num_classes, dtype=_d.get_default_dtype())[v.astype(jnp.int32)],
        "one_hot",
        x,
    )
