"""Comparison & logical ops. Reference: python/paddle/tensor/logic.py."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor
from . import apply_op

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "bitwise_and",
    "bitwise_or", "bitwise_xor", "bitwise_not", "bitwise_left_shift",
    "bitwise_right_shift", "is_empty", "is_tensor", "isreal", "iscomplex",
]


def _cmp(jfn, name):
    def op(x, y, name=None):
        return apply_op(lambda a, b: jfn(a, b), op.__name__, x, y)

    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")
bitwise_left_shift = _cmp(jnp.left_shift, "bitwise_left_shift")
bitwise_right_shift = _cmp(jnp.right_shift, "bitwise_right_shift")


def logical_not(x, name=None):
    return apply_op(jnp.logical_not, "logical_not", x)


def bitwise_not(x, name=None):
    return apply_op(jnp.bitwise_not, "bitwise_not", x)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def isreal(x, name=None):
    return apply_op(jnp.isreal, "isreal", x)


def iscomplex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)
