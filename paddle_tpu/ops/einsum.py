"""einsum. Reference: python/paddle/tensor/einsum.py — here a direct jnp.einsum
(XLA contracts on the MXU; no custom planner needed)."""
from __future__ import annotations

import jax.numpy as jnp

from . import apply_op

__all__ = ["einsum"]


def einsum(equation, *operands):
    return apply_op(lambda *vs: jnp.einsum(equation, *vs), "einsum", *operands)
