"""Pallas TPU kernels — the curated custom-kernel set SURVEY.md §7.1 calls for
(attention family first; XLA fusion covers the rest of the op surface).

Kernels run compiled on TPU and in interpreter mode elsewhere (CPU CI), so every
kernel is testable on the virtual-device mesh without hardware.
"""
from . import decode_attention  # noqa: F401
from . import flash_attention  # noqa: F401
