"""Flash attention (+ FlashMask) as Pallas TPU kernels.

Reference parity surface: python/paddle/nn/functional/flash_attention.py:358
(flash_attention), :1299 (flashmask_attention startend_row_indices encoding).
The reference binds an external CUDA flashattn library; here the kernel is
TPU-native Pallas (MXU matmuls, VMEM-resident K/V, f32 accumulation).

Design: grid over (batch*heads, q_blocks). Each grid step loads one q block
[BQ, D] plus the whole K/V [S, D] into VMEM and computes its exact softmax rows
— no online max/sum rescaling needed, while still never materialising the
[B, H, S, S] score tensor in HBM (that HBM round-trip is what makes the naive
path memory-bound at long S). K/V VMEM residency bounds S at ~8K for D=128
bf16; beyond that the sequence axis is sharded by ring attention
(paddle_tpu/distributed/context_parallel.py), which calls back into this kernel
per shard.

Backward is the standard two-kernel flash split: dq over q blocks, dk/dv over
k blocks, with delta = rowsum(dO * O) precomputed.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG = float(jnp.finfo(jnp.float32).min)


def _interpret() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _no_x64():
    """paddle_tpu enables jax_enable_x64 globally (paddle int64 dtype parity);
    under x64 pallas' internal index arithmetic emits i64 ops Mosaic cannot
    legalize. Kernel dtypes here are all explicit, so tracing the pallas_call
    with x64 off is semantics-preserving."""
    from jax.experimental import enable_x64

    return enable_x64(False)


# --------------------------------------------------------------------------- masks
def _allowed_mask(rows, cols, sri, causal: bool, seq: int):
    """(BQ, S) boolean mask of allowed positions; matches the semantics of the
    naive flashmask path (nn/functional/flash_attention.py flashmask_attention).

    rows/cols: int32 [BQ, S] query-row / key-col indices.
    sri: None or [S, n] int32 startend_row_indices for this (batch, head).
    """
    if causal:
        allowed = rows >= cols
    else:
        allowed = jnp.ones(rows.shape, jnp.bool_)
    if sri is None:
        return allowed
    n = sri.shape[-1]
    if causal:
        start = sri[:, 0][None, :]  # per-column mask start row
        if n == 1:
            masked = rows >= start
        else:
            end = sri[:, 1][None, :]
            masked = (rows >= start) & (rows < end)
        return allowed & ~masked
    lts = sri[:, 0][None, :]
    lte = sri[:, 1][None, :] if n > 1 else jnp.full_like(lts, seq)
    uts = sri[:, 2][None, :] if n > 2 else jnp.zeros_like(lts)
    ute = sri[:, 3][None, :] if n > 3 else jnp.zeros_like(lts)
    lower = (rows >= lts) & (rows < lte)
    upper = (rows >= uts) & (rows < ute)
    return allowed & ~(lower | upper)


def _row_col(qi, block_q: int, seq: int):
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, seq), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, seq), 1)
    return rows, cols


# --------------------------------------------------------------------------- fwd
def _fwd_kernel(*refs, scale, causal, block_q, seq, has_sri):
    scale = jnp.float32(scale)  # x64 mode: bare python floats promote f32->f64
    if has_sri:
        q_ref, k_ref, v_ref, sri_ref, o_ref, lse_ref = refs
        sri = sri_ref[0]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        sri = None
    qi = pl.program_id(1)
    # matmul INPUTS stay in the storage dtype (bf16 under AMP): the MXU runs
    # bf16×bf16→f32 at full rate, f32×f32 at half. Softmax statistics are f32
    # via preferred_element_type — the standard flash-attention precision split.
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    rows, cols = _row_col(qi, block_q, seq)
    allowed = _allowed_mask(rows, cols, sri, causal, seq)
    s = jnp.where(allowed, s, jnp.float32(_NEG))
    m = jnp.max(s, axis=1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=1, keepdims=True)
    o = jax.lax.dot_general(e.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    # Rows with no allowed position (possible under flashmask encodings) must
    # output exactly zero, not the uniform mean of V; lse=0 for such rows makes
    # backward's p = exp(_NEG - 0) = 0 so no gradient leaks through them.
    # NOT jnp.any: Mosaic lowers bool reduce_or via a float conversion in the
    # DEFAULT float dtype — f64 under jax_enable_x64 (which paddle_tpu sets
    # globally), and f64 vector reductions don't exist on TPU. An explicit f32
    # max-reduce lowers cleanly regardless of the x64 setting.
    any_allowed = jnp.max(allowed.astype(jnp.float32), axis=1,
                          keepdims=True) > jnp.float32(0.0)
    o = jnp.where(any_allowed, o / l, jnp.float32(0.0))
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0] = jnp.where(any_allowed, m + jnp.log(l), jnp.float32(0.0))


def _mha_fwd(q, k, v, sri, causal, scale, block_q):
    """q/k/v: [BH, S, D]; sri: [BH, S, n] int32 or None. Returns (out, lse)."""
    bh, seq, d = q.shape
    nq = seq // block_q
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q, seq=seq,
        has_sri=sri is not None,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if sri is not None:
        in_specs.append(pl.BlockSpec((1, seq, sri.shape[-1]), lambda b, i: (b, 0, 0)))
        args.append(sri)
    with _no_x64():
        out, lse = pl.pallas_call(
            kernel,
            grid=(bh, nq),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                # lse as a [BH, S, 1] column: block (1, BQ, 1) is legal TPU tiling
                # (lane dim equals the array's) and every kernel op stays 2D
                pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, seq, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(*args)
    return out, lse.reshape(bh, seq)


# --------------------------------------------------------------------------- bwd
def _dq_kernel(*refs, scale, causal, block_q, seq, has_sri):
    scale = jnp.float32(scale)
    if has_sri:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, sri_ref, dq_ref = refs
        sri = sri_ref[0]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref = refs
        sri = None
    qi = pl.program_id(1)
    # bf16 matmul inputs, f32 accumulation/statistics (see _fwd_kernel note)
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]    # (BQ, 1)
    delta = dl_ref[0]   # (BQ, 1)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    rows, cols = _row_col(qi, block_q, seq)
    allowed = _allowed_mask(rows, cols, sri, causal, seq)
    s = jnp.where(allowed, s, jnp.float32(_NEG))
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    dq = jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, causal, block_k, seq, has_sri):
    scale = jnp.float32(scale)
    if has_sri:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, sri_ref, dk_ref, dv_ref = refs
        sri_blk = sri_ref[0]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref = refs
        sri_blk = None
    ki = pl.program_id(1)
    # bf16 matmul inputs, f32 accumulation/statistics (see _fwd_kernel note)
    q = q_ref[0]                          # (S, D)
    k = k_ref[0]                          # (BK, D)
    v = v_ref[0]
    do = do_ref[0]                        # (S, D)
    lse = lse_ref[0]                      # (S, 1)
    delta = dl_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale  # (S, BK)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    allowed = _allowed_mask(rows, cols, sri_blk, causal, seq)
    s = jnp.where(allowed, s, jnp.float32(_NEG))
    p = jnp.exp(s - lse)
    dv = jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)  # (BK, D)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # (S, BK)
    ds = p * (dp - delta) * scale
    dk = jax.lax.dot_general(ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)  # (BK, D)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel_chunked(*refs, scale, causal, block_q, block_kc, seq):
    """dq for one q block, accumulated over k/v CHUNKS via the innermost grid
    dim — every tile is [block_q, block_kc], so VMEM stack use is independent
    of S (the full-sequence variant holds [block, S] f32 tiles and blows the
    16 MiB scoped limit at S=8192)."""
    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref = refs
    qi = pl.program_id(1)
    kc = pl.program_id(2)

    @pl.when(kc == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    row0 = qi * block_q
    col0 = kc * block_kc
    # causal: a chunk strictly above the diagonal contributes nothing
    live = jnp.logical_or(not causal, col0 <= row0 + block_q - 1)

    @pl.when(live)
    def _body():
        scale32 = jnp.float32(scale)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = dl_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale32
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        allowed = _allowed_mask(rows, cols, None, causal, seq)
        s = jnp.where(allowed, s, jnp.float32(_NEG))
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale32
        dq_ref[0] += jax.lax.dot_general(
            ds.astype(q.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _dkv_kernel_chunked(*refs, scale, causal, block_k, block_qc, seq):
    """dk/dv for one k block, accumulated over q/do CHUNKS (see
    _dq_kernel_chunked)."""
    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref = refs
    ki = pl.program_id(1)
    qc = pl.program_id(2)

    @pl.when(qc == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    row0 = qc * block_qc
    col0 = ki * block_k
    live = jnp.logical_or(not causal, col0 <= row0 + block_qc - 1)

    @pl.when(live)
    def _body():
        scale32 = jnp.float32(scale)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = dl_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale32
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        allowed = _allowed_mask(rows, cols, None, causal, seq)
        s = jnp.where(allowed, s, jnp.float32(_NEG))
        p = jnp.exp(s - lse)                                     # (QC, BK)
        dv_ref[0] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale32
        dk_ref[0] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _mha_bwd_chunked(q, k, v, out, lse, g, causal, scale):
    """Backward via chunk-accumulating kernels: VMEM-safe at any S (tiles are
    [512, 512] f32 regardless of sequence). Accumulation is f32 (the outputs
    are f32 and cast once at the end — bf16 += over S/512 chunks would lose
    precision)."""
    bh, seq, d = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse = lse.reshape(bh, seq, 1)
    delta = delta.reshape(bh, seq, 1)
    blk = 512
    n = seq // blk
    with _no_x64():
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_chunked, scale=scale, causal=causal,
                              block_q=blk, block_kc=blk, seq=seq),
            grid=(bh, n, n),
            in_specs=[
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0)),   # q
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, j, 0)),   # k
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, j, 0)),   # v
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0)),   # do
                pl.BlockSpec((1, blk, 1), lambda b, i, j: (b, i, 0)),   # lse
                pl.BlockSpec((1, blk, 1), lambda b, i, j: (b, i, 0)),   # delta
            ],
            out_specs=pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
            interpret=_interpret(),
        )(q, k, v, g, lse, delta)
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_chunked, scale=scale, causal=causal,
                              block_k=blk, block_qc=blk, seq=seq),
            grid=(bh, n, n),
            in_specs=[
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, j, 0)),   # q
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0)),   # k
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0)),   # v
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, j, 0)),   # do
                pl.BlockSpec((1, blk, 1), lambda b, i, j: (b, j, 0)),   # lse
                pl.BlockSpec((1, blk, 1), lambda b, i, j: (b, j, 0)),   # delta
            ],
            out_specs=[
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(k.shape, jnp.float32),
                jax.ShapeDtypeStruct(v.shape, jnp.float32),
            ],
            interpret=_interpret(),
        )(q, k, v, g, lse, delta)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _mha_bwd(q, k, v, sri, out, lse, g, causal, scale, block_q):
    bh, seq, d = q.shape
    if sri is None and seq > 4096 and seq % 512 == 0:
        # the full-sequence kernels hold [block, S] f32 score tiles — at
        # S=8192 that exceeds the 16 MiB VMEM scoped limit (measured on
        # v5e); the chunked variant's footprint is S-independent
        return _mha_bwd_chunked(q, k, v, out, lse, g, causal, scale)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse = lse.reshape(bh, seq, 1)
    delta = delta.reshape(bh, seq, 1)
    nq = seq // block_q
    has_sri = sri is not None

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),       # k
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),       # v
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # do
        pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),   # lse
        pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),   # delta
    ]
    dq_args = [q, k, v, g, lse, delta]
    if has_sri:
        dq_in_specs.append(pl.BlockSpec((1, seq, sri.shape[-1]), lambda b, i: (b, 0, 0)))
        dq_args.append(sri)
    with _no_x64():
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, causal=causal, block_q=block_q,
                              seq=seq, has_sri=has_sri),
            grid=(bh, nq),
            in_specs=dq_in_specs,
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=_interpret(),
        )(*dq_args)

    dkv_in_specs = [
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),       # q
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # k block
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # v block
        pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),       # do
        pl.BlockSpec((1, seq, 1), lambda b, i: (b, 0, 0)),       # lse
        pl.BlockSpec((1, seq, 1), lambda b, i: (b, 0, 0)),       # delta
    ]
    dkv_args = [q, k, v, g, lse, delta]
    if has_sri:
        # sri is indexed by key column: this kernel only sees its k block's columns
        dkv_in_specs.append(
            pl.BlockSpec((1, block_q, sri.shape[-1]), lambda b, i: (b, i, 0))
        )
        dkv_args.append(sri)
    with _no_x64():
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, causal=causal, block_k=block_q,
                              seq=seq, has_sri=has_sri),
            grid=(bh, nq),
            in_specs=dkv_in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
            ],
            interpret=_interpret(),
        )(*dkv_args)
    return dq, dk, dv


# ------------------------------------------------------------------- custom vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, block_q):
    out, _ = _mha_fwd(q, k, v, None, causal, scale, block_q)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q):
    out, lse = _mha_fwd(q, k, v, None, causal, scale, block_q)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, res, g):
    q, k, v, out, lse = res
    return _mha_bwd(q, k, v, None, out, lse, g, causal, scale, block_q)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_masked(q, k, v, sri, causal, scale, block_q):
    out, _ = _mha_fwd(q, k, v, sri, causal, scale, block_q)
    return out


def _flash_masked_fwd(q, k, v, sri, causal, scale, block_q):
    out, lse = _mha_fwd(q, k, v, sri, causal, scale, block_q)
    return out, (q, k, v, sri, out, lse)


def _flash_masked_bwd(causal, scale, block_q, res, g):
    q, k, v, sri, out, lse = res
    dq, dk, dv = _mha_bwd(q, k, v, sri, out, lse, g, causal, scale, block_q)
    dsri = np.zeros(sri.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dsri


_flash_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


# ------------------------------------------------------------------ public API
def _to_bhsd(x):
    """[B, S, H, D] -> [B*H, S, D] (paddle flash layout -> kernel layout)."""
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


def _repeat_kv(kv, n_rep):
    if n_rep == 1:
        return kv
    return jnp.repeat(kv, n_rep, axis=2)


def _auto_block_q(seq: int) -> int:
    """Largest q-block that divides the sequence and keeps the [BQ, S] f32
    score tile within a conservative VMEM budget. Bigger blocks amortize the
    K/V VMEM loads over more MXU work — measured on v5e (GPT-350M, S=1024):
    128→42.9% MFU, 256→46.9%, 512→49.2%, 1024→50.7%."""
    # 4 MiB f32 score-tile budget: the BACKWARD dkv kernel holds two [S, BK]
    # f32 tiles (p and dp) plus full-sequence q/do, so the fwd-only 8 MiB
    # budget VMEM-OOMs at S=8192 (measured: 36 KB over the 16 MiB stack)
    budget = 4 * 2**20
    for bq in (1024, 512, 256, 128):
        if seq % bq == 0 and bq * seq * 4 <= budget:
            return bq
    return 128


def supports(q_shape, k_shape, block_q=128) -> bool:
    """Static check: can the kernel run these shapes (self-attention, divisible seq)."""
    b, s, h, d = q_shape
    return (
        s == k_shape[1] and s % block_q == 0 and s >= block_q
        and d <= 256 and q_shape[0] == k_shape[0]
    )


def flash_attention(q, k, v, causal=False, scale=None, block_q=None):
    """Pallas flash attention over paddle layout [B, S, H, D]; GQA via kv-head
    broadcast. Differentiable (custom VJP flash backward)."""
    b, s, h, d = q.shape
    if block_q is None:
        block_q = _auto_block_q(s)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    out = _flash(_to_bhsd(q), _to_bhsd(k), _to_bhsd(v), bool(causal), float(scale),
                 int(block_q))
    return _from_bhsd(out, b, h)


def flashmask_attention(q, k, v, startend_row_indices, causal=True, scale=None,
                        block_q=None):
    """FlashMask (reference flash_attention.py:1299): startend_row_indices
    [B, H'|1, S, n] sparse-mask encoding evaluated inside the kernel — no
    [B, H, S, S] mask materialisation."""
    b, s, h, d = q.shape
    if block_q is None:
        block_q = _auto_block_q(s)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    sri = startend_row_indices.astype(jnp.int32)
    hp = sri.shape[1]
    if hp == 1 and h > 1:
        sri = jnp.broadcast_to(sri, (b, h, sri.shape[2], sri.shape[3]))
    sri = sri.reshape(b * h, sri.shape[2], sri.shape[3])
    out = _flash_masked(_to_bhsd(q), _to_bhsd(k), _to_bhsd(v), sri, bool(causal),
                        float(scale), int(block_q))
    return _from_bhsd(out, b, h)
