"""Split-KV decode attention (flash-decode style) + paged variant, as Pallas
TPU kernels.

Reference parity surface: the LLM-serving kernels the reference binds from
CUDA — masked_multihead_attention_kernel.cu:1201 (single-token attention over
a dense cache) and block_multi_head_attention_kernel.cu (paged / block-table
cache). Here both are TPU-native Pallas.

Design (Flash-Decoding, Dao et al. 2023): decode attention at small batch is
memory-bandwidth-bound — one query row per (batch, head) must stream the whole
KV prefix. A single-block kernel would serialize that stream; instead the KV
prefix is PARTITIONED across grid blocks:

  stage 1 (Pallas): grid (B*Hkv, T/block_k). Each step loads one contiguous
    [block_k, D] KV block into VMEM once and computes the block-local softmax
    statistics for its head's query group — running max m, normalizer l, and
    the unnormalized partial output o = e @ V (the classic (m, l, o) flash
    triple), written per block.
  stage 2 (XLA): the per-block partials are combined with the standard
    rescaling reduction: m* = max_j m_j, out = sum_j o_j e^{m_j - m*} /
    sum_j l_j e^{m_j - m*}. The partials are [BH, nb, rows, D] — a few
    hundred KB — so this reduction is noise; XLA fuses it into one kernel.

Layout contract: caches are HEAD-LEADING — [B, Hkv, T, D] dense, [Hkv, P,
BS, D] paged — so every kernel block is a plain (1, rows, D) / (1, 1, BS, D)
tile over the two minor dims and the head axis is resolved by the grid /
index_map, never sliced in-kernel (in-kernel head slicing would relayout the
whole block per head under Mosaic; this is the same 3-D-block idiom as
flash_attention.py and the shape the DMA engine streams contiguously). The
models pay only a [B, S, Hkv, D] -> [B, Hkv, S, D] transpose of the NEW rows
per step — S is 1 at decode.

GQA is native: q rows are grouped per kv head ([B*Hkv, S*G, D], G =
num_q_heads / num_kv_heads), so K/V are never materialized at the
`rep`-expanded shape the old jnp.repeat path paid G× cache traffic for.

Masked length: `lengths` (per-request int32 [B]) bounds the live prefix —
padded cache slots are masked in-kernel (col <= length + row//G), never
gathered. Blocks entirely past the live region skip compute via pl.when.

The paged variant reads KV through per-request block tables
(PrefetchScalarGridSpec: the table is scalar-prefetched so the BlockSpec
index_map itself selects the page, PagedAttention-style) — the serving
layer's block-paged KV cache (paddle_tpu/inference/kv_cache.py) feeds it.

Everything runs compiled on TPU and in interpreter mode elsewhere (CPU CI).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret, _no_x64

_NEG = float(jnp.finfo(jnp.float32).min)


# ----------------------------------------------------------------- reference
def decode_attention_xla(q, k_cache, v_cache, lengths, scale=None):
    """Grouped-GQA cache attention in plain XLA — the correctness reference
    and the `decode_kernel="xla"` serving path.

    q: [B, S, Hq, D] at absolute positions length..length+S-1.
    k_cache/v_cache: [B, Hkv, T, D] (head-leading); entries [0, length+S) are
    live (the S new rows were just written at [length, length+S)).
    lengths: int32 scalar or [B] — per-request live-prefix length.

    The q-head axis is grouped over kv heads via einsum ("bsngd,bntd->bngst"),
    so K/V are consumed at their stored [B, Hkv, T, D] shape — no jnp.repeat
    materialization of the G-expanded heads.
    """
    B, S, Hq, D = q.shape
    Hkv, T = k_cache.shape[1], k_cache.shape[2]
    G = Hq // Hkv
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    lengths = _norm_lengths(lengths, B)
    qg = q.reshape(B, S, Hkv, G, D)
    scores = jnp.einsum("bsngd,bntd->bngst", qg, k_cache,
                        preferred_element_type=jnp.float32) * jnp.float32(scale)
    pos_q = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B,S]
    pos_k = jnp.arange(T, dtype=jnp.int32)
    allowed = pos_k[None, None, :] <= pos_q[:, :, None]          # [B,S,T]
    scores = jnp.where(allowed[:, None, None], scores, jnp.float32(_NEG))
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bngst,bntd->bsngd", probs, v_cache)
    return out.reshape(B, S, Hq, D).astype(q.dtype)


def _norm_lengths(lengths, B):
    lengths = jnp.asarray(lengths, jnp.int32).reshape(-1)
    return jnp.broadcast_to(lengths, (B,))


# -------------------------------------------------------------- kernel body
def _partials_body(length, col0, q, k, v, o_ref, m_ref, l_ref, *, scale, g):
    """Block-local (m, l, o) partials for one (batch*head, kv-block) step.
    q: [SG, D] (S query steps × G grouped q heads, row-major (s, g));
    k/v: [BK, D]."""
    sg, bk = q.shape[0], k.shape[0]
    scale32 = jnp.float32(scale)
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (sg, bk), 1)
    rloc = jax.lax.broadcasted_iota(jnp.int32, (sg, bk), 0)
    # row r is query step s = r//G at absolute position length + s — causal
    # over the live prefix + the new rows
    qrow = jax.lax.div(rloc, jnp.int32(g)) if g > 1 else rloc
    allowed = cols <= length + qrow
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale32
    s = jnp.where(allowed, s, jnp.float32(_NEG))
    m = jnp.max(s, axis=1, keepdims=True)
    # e must be exactly 0 on masked cols even when the WHOLE block is masked
    # for a row (m == _NEG would make exp(s - m) = 1 there)
    e = jnp.where(allowed, jnp.exp(s - m), jnp.float32(0.0))
    l = jnp.sum(e, axis=1, keepdims=True)
    o = jax.lax.dot_general(e.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = o
    m_ref[0, 0] = m
    l_ref[0, 0] = l


def _write_dead(o_ref, m_ref, l_ref):
    # partials that contribute nothing under the stage-2 rescale
    o_ref[0, 0] = jnp.zeros_like(o_ref[0, 0])
    m_ref[0, 0] = jnp.full_like(m_ref[0, 0], jnp.float32(_NEG))
    l_ref[0, 0] = jnp.zeros_like(l_ref[0, 0])


def _splitkv_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *,
                    scale, block_k, hkv, g, s_new):
    j = pl.program_id(1)
    bh = pl.program_id(0)
    length = len_ref[jax.lax.div(bh, jnp.int32(hkv)), 0]
    col0 = j * block_k
    live = col0 < length + s_new

    @pl.when(live)
    def _body():
        _partials_body(length, col0, q_ref[0], k_ref[0], v_ref[0],
                       o_ref, m_ref, l_ref, scale=scale, g=g)

    @pl.when(jnp.logical_not(live))
    def _dead():
        _write_dead(o_ref, m_ref, l_ref)


def _combine_partials(o_p, m_p, l_p, B, Hkv, S, G, D, dtype):
    """Stage 2: rescale-and-sum the per-block (m, l, o) partials (XLA — the
    reduction is over [nb] of tiny tiles; one fused kernel)."""
    m_star = jnp.max(m_p, axis=1, keepdims=True)
    w = jnp.exp(m_p - m_star)
    l_star = jnp.sum(l_p * w, axis=1)               # [BH, SG, 1]
    o = jnp.sum(o_p * w, axis=1)                    # [BH, SG, D]
    out = jnp.where(l_star > 0, o / jnp.where(l_star > 0, l_star, 1.0), 0.0)
    out = out.reshape(B, Hkv, S, G, D).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, S, Hkv * G, D).astype(dtype)


def _q_rows(q, Hkv, G):
    """[B, S, Hq, D] -> [B*Hkv, S*G, D], rows grouped per kv head
    (h = n*G + g)."""
    B, S, Hq, D = q.shape
    return (q.reshape(B, S, Hkv, G, D).transpose(0, 2, 1, 3, 4)
            .reshape(B * Hkv, S * G, D))


def auto_block_k(T: int) -> int | None:
    """Largest KV block that divides the cache length. Bigger blocks amortize
    grid/DMA overhead; smaller ones give stage 1 more parallelism — 256 is the
    sweet spot for bandwidth-bound decode on v5e-class chips (512 KB of KV in
    flight per step at D=64 bf16 under double buffering)."""
    for bk in (256, 512, 128, 64):
        if T % bk == 0 and T >= bk:
            return bk
    return T if T <= 1024 else None


def supports(q_shape, cache_shape, block_k=None) -> bool:
    """Static check: can the split-KV kernel run these shapes.
    cache_shape is head-leading [B, Hkv, T, D]."""
    B, S, Hq, D = q_shape
    Hkv, T = cache_shape[1], cache_shape[2]
    bk = block_k or auto_block_k(T)
    return (bk is not None and T % bk == 0 and D <= 256
            and Hq % Hkv == 0 and cache_shape[0] == B)


def decode_attention(q, k_cache, v_cache, lengths, scale=None, block_k=None,
                     kernel="pallas"):
    """Decode attention over a dense per-request KV cache.

    q [B, S, Hq, D]; caches [B, Hkv, T, D] (head-leading); lengths int32
    scalar or [B]. kernel: "pallas" (split-KV flash-decode) | "xla" (grouped
    einsum reference). Pallas falls back to XLA when shapes are unsupported.
    """
    B, S, Hq, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if kernel != "pallas" or not supports(q.shape, k_cache.shape, block_k):
        return decode_attention_xla(q, k_cache, v_cache, lengths, scale)
    Hkv, T = k_cache.shape[1], k_cache.shape[2]
    G = Hq // Hkv
    bk = block_k or auto_block_k(T)
    nb = T // bk
    sg = S * G
    BH = B * Hkv
    lengths = _norm_lengths(lengths, B).reshape(B, 1)
    qr = _q_rows(q.astype(k_cache.dtype), Hkv, G)
    kf = k_cache.reshape(BH, T, D)
    vf = v_cache.reshape(BH, T, D)
    kernel_fn = functools.partial(_splitkv_kernel, scale=float(scale),
                                  block_k=bk, hkv=Hkv, g=G, s_new=S)
    with _no_x64():
        o_p, m_p, l_p = pl.pallas_call(
            kernel_fn,
            grid=(BH, nb),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),       # lengths [B, 1]
                pl.BlockSpec((1, sg, D), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, sg, D), lambda b, j: (b, j, 0, 0)),
                pl.BlockSpec((1, 1, sg, 1), lambda b, j: (b, j, 0, 0)),
                pl.BlockSpec((1, 1, sg, 1), lambda b, j: (b, j, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((BH, nb, sg, D), jnp.float32),
                jax.ShapeDtypeStruct((BH, nb, sg, 1), jnp.float32),
                jax.ShapeDtypeStruct((BH, nb, sg, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(lengths, qr, kf, vf)
    return _combine_partials(o_p, m_p, l_p, B, Hkv, S, G, D, q.dtype)


# ------------------------------------------------------------------- paged
def _paged_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                  *, scale, block_size, g, s_new):
    """Same math as _splitkv_kernel over a 3-D (batch, head, kv-block) grid;
    the KV block arrived via the block-table-driven index_map (page
    tbl[b, j]), col0 = j * block_size."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    length = len_ref[b]
    col0 = j * block_size
    live = col0 < length + s_new

    @pl.when(live)
    def _body():
        _partials_body(length, col0, q_ref[0, 0], k_ref[0, 0], v_ref[0, 0],
                       o_ref.at[0], m_ref.at[0], l_ref.at[0], scale=scale, g=g)

    @pl.when(jnp.logical_not(live))
    def _dead():
        _write_dead(o_ref.at[0], m_ref.at[0], l_ref.at[0])


def _tp_shard_mesh(Hq, Hkv):
    """The active jax mesh iff it carries a tp axis that head-shards this
    shape: tp > 1 dividing Hkv (Hq follows — GQA groups are contiguous, so a
    block-shard of Hq aligns with the local kv heads). None otherwise."""
    from ...distributed.mesh import current_jax_mesh, mesh_axis_size

    jm = current_jax_mesh()
    if jm is None or "tp" not in jm.axis_names:
        return None
    tp = mesh_axis_size("tp", jm)
    if tp <= 1 or Hkv % tp != 0 or Hq % Hkv != 0:
        return None
    return jm


def paged_decode_attention(q, k_pages, v_pages, block_tables, lengths,
                           scale=None, kernel="pallas"):
    """Decode attention reading KV through per-request block tables.

    q: [B, S, Hq, D]; k_pages/v_pages: [Hkv, P, BS, D] (the shared
    head-leading page pool); block_tables: [B, NB] int32 page ids (entries
    past a request's extent must still be VALID page ids, e.g. 0 — they are
    fetched but fully masked); lengths: [B] int32 live prefix per request.

    Pallas path: PrefetchScalarGridSpec prefetches the table so the k/v
    BlockSpec index_map picks page tbl[b, j] directly — the PagedAttention
    access pattern, no gather materialization.

    Under a serving mesh with a tp axis (ISSUE-12), the whole call shard_maps
    over the head axis: each chip runs the split-KV kernel on its LOCAL heads
    against its LOCAL pool shard (attention is head-local, so no collective is
    needed here — the only cross-chip exchange per launch is the sampled-logit
    gather after the vocab-sharded lm_head).
    """
    B, S, Hq, D = q.shape
    Hkv = k_pages.shape[0]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    jm = _tp_shard_mesh(Hq, Hkv)
    if jm is not None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        inner = functools.partial(_paged_decode_attention_impl,
                                  scale=float(scale), kernel=kernel)
        fn = shard_map(
            inner, mesh=jm,
            in_specs=(P(None, None, "tp", None), P("tp"), P("tp"),
                      P(None, None), P(None)),
            out_specs=P(None, None, "tp", None),
            check_rep=False)
        return fn(q, k_pages, v_pages,
                  jnp.asarray(block_tables, jnp.int32),
                  _norm_lengths(lengths, B))
    return _paged_decode_attention_impl(q, k_pages, v_pages, block_tables,
                                        lengths, scale=scale, kernel=kernel)


def _paged_decode_attention_impl(q, k_pages, v_pages, block_tables, lengths,
                                 scale=None, kernel="pallas"):
    B, S, Hq, D = q.shape
    Hkv, P_, BS = k_pages.shape[0], k_pages.shape[1], k_pages.shape[2]
    NB = block_tables.shape[1]
    G = Hq // Hkv
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    lengths = _norm_lengths(lengths, B)
    block_tables = jnp.asarray(block_tables, jnp.int32)
    if kernel != "pallas" or D > 256 or Hq % Hkv != 0:
        # gather-based reference: pages -> contiguous head-leading dense cache
        k_dense = (k_pages[:, block_tables]        # [Hkv, B, NB, BS, D]
                   .reshape(Hkv, B, NB * BS, D).swapaxes(0, 1))
        v_dense = (v_pages[:, block_tables]
                   .reshape(Hkv, B, NB * BS, D).swapaxes(0, 1))
        return decode_attention_xla(q, k_dense, v_dense, lengths, scale)
    sg = S * G
    BH = B * Hkv
    # [B, Hkv, sg, D]: the 3-D (batch, head, block) grid indexes heads
    # directly — no index_map arithmetic (python // or % on a traced grid
    # index promotes through an i64 helper under the global x64 flag)
    qr = _q_rows(q.astype(k_pages.dtype), Hkv, G).reshape(B, Hkv, sg, D)
    kernel_fn = functools.partial(_paged_kernel, scale=float(scale),
                                  block_size=BS, g=G, s_new=S)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,            # block_tables, lengths
        grid=(B, Hkv, NB),
        in_specs=[
            pl.BlockSpec((1, 1, sg, D), lambda b, h, j, tbl, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, BS, D),
                         lambda b, h, j, tbl, ln: (h, tbl[b, j], 0, 0)),
            pl.BlockSpec((1, 1, BS, D),
                         lambda b, h, j, tbl, ln: (h, tbl[b, j], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, sg, D),
                         lambda b, h, j, tbl, ln: (b, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, sg, 1),
                         lambda b, h, j, tbl, ln: (b, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, sg, 1),
                         lambda b, h, j, tbl, ln: (b, h, j, 0, 0)),
        ],
    )
    with _no_x64():
        o_p, m_p, l_p = pl.pallas_call(
            kernel_fn,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B, Hkv, NB, sg, D), jnp.float32),
                jax.ShapeDtypeStruct((B, Hkv, NB, sg, 1), jnp.float32),
                jax.ShapeDtypeStruct((B, Hkv, NB, sg, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(block_tables, lengths, qr, k_pages, v_pages)
    o_p = o_p.reshape(BH, NB, sg, D)
    m_p = m_p.reshape(BH, NB, sg, 1)
    l_p = l_p.reshape(BH, NB, sg, 1)
    return _combine_partials(o_p, m_p, l_p, B, Hkv, S, G, D, q.dtype)


def paged_cache_update(k_pages, v_pages, k_new, v_new, block_tables,
                       positions):
    """Scatter S new KV rows per request into the page pool at their (page,
    slot) targets. k_pages/v_pages: [Hkv, P, BS, D]; k_new/v_new:
    [B, S, Hkv, D]; `positions` is [B, S] int32 absolute cache positions; rows
    at position >= NB*BS (see `write_positions`) get a poisoned page id so
    XLA's out-of-bounds scatter DROPS them — that is how mixed-length prompts
    padded to a common S skip their padding rows without a mask gather."""
    BS = k_pages.shape[2]
    NB = block_tables.shape[1]
    pos = jnp.asarray(positions, jnp.int32)
    page = jnp.take_along_axis(block_tables.astype(jnp.int32),
                               jnp.clip(pos // BS, 0, NB - 1), axis=1)
    page = jnp.where(pos < NB * BS, page, jnp.int32(k_pages.shape[1]))
    slot = pos % BS
    # [B, S, Hkv, D] -> [Hkv, B, S, D] so the (page, slot) index arrays land
    # on the pool's middle axes under one leading full slice
    k_vals = k_new.astype(k_pages.dtype).transpose(2, 0, 1, 3)
    v_vals = v_new.astype(v_pages.dtype).transpose(2, 0, 1, 3)
    k_pages = k_pages.at[:, page, slot].set(k_vals, mode="drop")
    v_pages = v_pages.at[:, page, slot].set(v_vals, mode="drop")
    # keep the pool head-sharded over tp through the scatter so the step
    # programs' committed outputs preserve the serving-mesh layout (no-op
    # without a tp mesh — `constrain` drops absent/non-dividing axes)
    from ...distributed.mesh import constrain

    k_pages = constrain(k_pages, ["tp", None, None, None])
    v_pages = constrain(v_pages, ["tp", None, None, None])
    return k_pages, v_pages


def write_positions(lengths, S, valid=None, capacity=None):
    """[B, S] absolute write positions starting at each request's length;
    rows where `valid` is False are pushed to `capacity` (= NB*BS) so
    paged_cache_update drops them."""
    B = jnp.asarray(lengths).reshape(-1).shape[0]
    pos = _norm_lengths(lengths, B)[:, None] + jnp.arange(S, dtype=jnp.int32)
    if valid is None:
        return pos
    return jnp.where(valid, pos, jnp.int32(capacity))
