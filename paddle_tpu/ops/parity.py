"""Export-parity fill-ins: the remaining `paddle.*` surface.

Reference: python/paddle/__init__.py (435 exports) / python/paddle/tensor/*.
Three groups:
1. small ops the round-1..3 sets skipped (stacking/splitting variants,
   scatter-into views, special functions, dlpack, constants);
2. in-place variants (`op_`): paddle mutates the tensor and keeps autograd —
   here the base op runs and the result is grafted back into the same Tensor
   (value + tape linkage), which is semantically identical under the tape;
3. environment shims (printoptions, LazyGuard, signal handler) that are
   no-ops or thin state in the trace-and-compile world (documented each).
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, to_tensor
from . import apply_op

__all__ = [
    # stacking / splitting
    "add_n", "block_diag", "column_stack", "row_stack", "hstack", "vstack",
    "dstack", "hsplit", "vsplit", "dsplit", "tensor_split", "cartesian_prod",
    "combinations", "unflatten", "as_strided", "matrix_transpose", "reverse",
    # scatter-into-view family
    "diagonal_scatter", "select_scatter", "slice_scatter", "index_fill",
    "index_fill_",
    # special functions / math
    "gammaln", "gammainc", "gammaincc", "multigammaln", "polygamma", "i0e",
    "i1", "i1e", "sinc", "polar", "frexp", "signbit", "isin", "isneginf",
    "isposinf", "histogram_bin_edges", "renorm", "reduce_as",
    "negative", "positive", "less", "floor_mod", "pdist", "cdist",
    # dlpack + misc env
    "from_dlpack", "to_dlpack", "set_printoptions", "disable_signal_handler",
    "check_shape", "LazyGuard", "create_parameter", "rank", "shape",
    "get_cuda_rng_state", "set_cuda_rng_state",
    # constants / dtypes
    "pi", "e", "inf", "nan", "newaxis", "float8_e4m3fn", "float8_e5m2",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ------------------------------------------------------------- stacks/splits
def add_n(inputs, name=None):
    """Reference: tensor/math.py add_n — elementwise sum of a tensor list."""
    items = inputs if isinstance(inputs, (list, tuple)) else [inputs]

    def f(*vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out

    return apply_op(f, "add_n", *items)


def block_diag(inputs, name=None):
    def f(*vals):
        vals = [v.reshape(1, -1) if v.ndim <= 1 else v for v in vals]
        rows = sum(v.shape[0] for v in vals)
        cols = sum(v.shape[1] for v in vals)
        out = jnp.zeros((rows, cols), vals[0].dtype)
        r = c = 0
        for v in vals:
            out = jax.lax.dynamic_update_slice(out, v.astype(out.dtype), (r, c))
            r += v.shape[0]
            c += v.shape[1]
        return out

    return apply_op(f, "block_diag", *inputs)


def column_stack(x, name=None):
    def f(*vals):
        vals = [v[:, None] if v.ndim == 1 else v for v in vals]
        return jnp.concatenate(vals, axis=1)

    return apply_op(f, "column_stack", *x)


def row_stack(x, name=None):
    return apply_op(lambda *v: jnp.vstack(v), "row_stack", *x)


def hstack(x, name=None):
    return apply_op(lambda *v: jnp.hstack(v), "hstack", *x)


def vstack(x, name=None):
    return apply_op(lambda *v: jnp.vstack(v), "vstack", *x)


def dstack(x, name=None):
    return apply_op(lambda *v: jnp.dstack(v), "dstack", *x)


def _split_like(fn_name, jfn):
    def f(x, num_or_indices, name=None):
        n = (tuple(num_or_indices) if isinstance(num_or_indices, (list, tuple))
             else num_or_indices)
        out = apply_op(lambda v: list(jfn(v, n)), fn_name, x)
        return out if isinstance(out, list) else [out]

    f.__name__ = fn_name
    return f


hsplit = _split_like("hsplit", jnp.hsplit)
vsplit = _split_like("vsplit", jnp.vsplit)
dsplit = _split_like("dsplit", jnp.dsplit)


def tensor_split(x, num_or_indices, axis=0, name=None):
    n = (tuple(num_or_indices) if isinstance(num_or_indices, (list, tuple))
         else num_or_indices)
    return apply_op(lambda v: list(jnp.array_split(v, n, axis=axis)),
                    "tensor_split", x)


def cartesian_prod(x, name=None):
    def f(*vals):
        grids = jnp.meshgrid(*vals, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    return apply_op(f, "cartesian_prod", *x)


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools

    n = int(_val(x).shape[0])
    gen = (itertools.combinations_with_replacement(range(n), r)
           if with_replacement else itertools.combinations(range(n), r))
    idx = np.array(list(gen), dtype=np.int64).reshape(-1, r)
    return apply_op(lambda v: v[jnp.asarray(idx)], "combinations", x)


def unflatten(x, axis, shape, name=None):
    def f(v):
        ax = axis % v.ndim
        new = list(v.shape[:ax]) + list(shape) + list(v.shape[ax + 1:])
        return v.reshape(new)

    return apply_op(f, "unflatten", x)


def as_strided(x, shape, stride, offset=0, name=None):
    """View by explicit strides (reference: tensor/manipulation as_strided
    over the stride kernels). Gather-based on TPU (no raw pointers)."""
    def f(v):
        flat = v.reshape(-1)
        idx = jnp.full((), offset, jnp.int64)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
        lin = sum(g.astype(jnp.int64) * st for g, st in zip(grids, stride))
        return flat[idx + lin]

    return apply_op(f, "as_strided", x)


def matrix_transpose(x, name=None):
    return apply_op(lambda v: jnp.swapaxes(v, -1, -2), "matrix_transpose", x)


def reverse(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op(lambda v: jnp.flip(v, ax), "reverse", x)


# --------------------------------------------------------- scatter-into-view
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(v, src):
        ii, jj = jnp.diag_indices(min(v.shape[axis1], v.shape[axis2]))
        if offset >= 0:
            ii, jj = ii[: v.shape[axis2] - offset], jj[: v.shape[axis2] - offset] + offset
        else:
            ii, jj = ii[: v.shape[axis1] + offset] - offset, jj[: v.shape[axis1] + offset]
        moved = jnp.moveaxis(v, (axis1, axis2), (0, 1))
        moved = moved.at[ii, jj].set(src.astype(v.dtype))
        return jnp.moveaxis(moved, (0, 1), (axis1, axis2))

    return apply_op(f, "diagonal_scatter", x, y)


def select_scatter(x, values, axis, index, name=None):
    def f(v, src):
        idx = [slice(None)] * v.ndim
        idx[axis] = index
        return v.at[tuple(idx)].set(src.astype(v.dtype))

    return apply_op(f, "select_scatter", x, values)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(v, src):
        idx = [slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sd)
        return v.at[tuple(idx)].set(src.astype(v.dtype))

    return apply_op(f, "slice_scatter", x, value)


def index_fill(x, index, axis, fill_value, name=None):
    def f(v, idx):
        moved = jnp.moveaxis(v, axis, 0)
        moved = moved.at[idx].set(jnp.asarray(fill_value, v.dtype))
        return jnp.moveaxis(moved, 0, axis)

    return apply_op(f, "index_fill", x, index)


def index_fill_(x, index, axis, fill_value, name=None):
    out = index_fill(x, index, axis, fill_value)
    return _graft(x, out)


# ------------------------------------------------------------------ special
def gammaln(x, name=None):
    from jax.scipy.special import gammaln as f

    return apply_op(lambda v: f(v.astype(jnp.float32) if not
                                jnp.issubdtype(v.dtype, jnp.floating) else v),
                    "gammaln", x)


def gammainc(x, y, name=None):
    from jax.scipy.special import gammainc as f

    return apply_op(f, "gammainc", x, y)


def gammaincc(x, y, name=None):
    from jax.scipy.special import gammaincc as f

    return apply_op(f, "gammaincc", x, y)


def multigammaln(x, p, name=None):
    from jax.scipy.special import gammaln as g

    def f(v):
        i = jnp.arange(1, p + 1, dtype=jnp.float32)
        return (p * (p - 1) / 4.0 * _math.log(_math.pi)
                + g(v[..., None] + (1.0 - i) / 2.0).sum(-1))

    return apply_op(f, "multigammaln", x)


def polygamma(x, n, name=None):
    from jax.scipy.special import polygamma as f

    return apply_op(lambda v: f(n, v), "polygamma", x)


def i0e(x, name=None):
    from jax.scipy.special import i0e as f

    return apply_op(f, "i0e", x)


def i1(x, name=None):
    from jax.scipy.special import i1 as f

    return apply_op(f, "i1", x)


def i1e(x, name=None):
    from jax.scipy.special import i1e as f

    return apply_op(f, "i1e", x)


def sinc(x, name=None):
    return apply_op(jnp.sinc, "sinc", x)


def polar(abs, angle, name=None):
    def f(r, t):
        return (r * jnp.cos(t) + 1j * r * jnp.sin(t)).astype(jnp.complex64)

    return apply_op(f, "polar", abs, angle)


def frexp(x, name=None):
    def f(v):
        m, e = jnp.frexp(v)
        return m, e.astype(jnp.int32)

    return apply_op(f, "frexp", x, nout=2)


def signbit(x, name=None):
    return apply_op(jnp.signbit, "signbit", x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply_op(lambda v, t: jnp.isin(v, t, invert=invert), "isin",
                    x, test_x)


def isneginf(x, name=None):
    return apply_op(jnp.isneginf, "isneginf", x)


def isposinf(x, name=None):
    return apply_op(jnp.isposinf, "isposinf", x)


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    def f(v):
        lo, hi = (jnp.min(v), jnp.max(v)) if min == 0 and max == 0 else (min, max)
        return jnp.linspace(lo, hi, bins + 1).astype(jnp.float32)

    return apply_op(f, "histogram_bin_edges", input)


def renorm(x, p, axis, max_norm, name=None):
    def f(v):
        moved = jnp.moveaxis(v, axis, 0).reshape(v.shape[axis], -1)
        norms = jnp.sum(jnp.abs(moved) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = moved * scale[:, None]
        return jnp.moveaxis(out.reshape(jnp.moveaxis(v, axis, 0).shape), 0, axis)

    return apply_op(f, "renorm", x)


def reduce_as(x, target, name=None):
    """Sum-reduce `x` to `target`'s shape (reference: reduce_as op)."""
    tgt = tuple(_val(target).shape)

    def f(v):
        out = v
        while out.ndim > len(tgt):
            out = out.sum(0)
        for i, (a, b) in enumerate(zip(out.shape, tgt)):
            if a != b:
                out = out.sum(i, keepdims=True)
        return out

    return apply_op(f, "reduce_as", x)


def less(x, y, name=None):
    """Alias of less_than (reference exports both)."""
    from .logic import less_than

    return less_than(x, y)


def floor_mod(x, y, name=None):
    """Alias of mod (reference exports both)."""
    from .math import mod

    return mod(x, y)


def negative(x, name=None):
    return apply_op(jnp.negative, "negative", x)


def positive(x, name=None):
    return apply_op(lambda v: +v, "positive", x)


def pdist(x, p=2.0, name=None):
    from ..nn.functional.common import pdist as f

    return f(x, p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    from ..nn.functional.common import cdist as f

    return f(x, y, p, compute_mode)


# ----------------------------------------------------------------- env shims
def from_dlpack(dlpack):
    return Tensor(jnp.from_dlpack(dlpack))


def to_dlpack(x):
    return jax.dlpack.to_dlpack(_val(x)) if hasattr(jax, "dlpack") else _val(x)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Maps to numpy printoptions (Tensor repr prints via numpy)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    """Reference: disables paddle's C++ signal handlers; here the only
    installed handler is faulthandler's SIGUSR1 dump — unregister it."""
    import faulthandler
    import signal as _signal

    try:
        faulthandler.unregister(_signal.SIGUSR1)
    except Exception:
        pass


def check_shape(x):  # static-graph debug helper; shape is always concrete here
    return list(_val(x).shape)


class LazyGuard:
    """Reference framework/LazyGuard: delay parameter init until first call.
    Parameters here are created eagerly but cheaply (jax arrays are lazy until
    used) — kept as a no-op context for API compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.layer import Layer

    helper = Layer()
    return helper.create_parameter(list(shape), attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def rank(input):
    return to_tensor(np.asarray(_val(input).ndim, np.int32))


def shape(input):
    return to_tensor(np.asarray(_val(input).shape, np.int64))


def get_cuda_rng_state():
    from ..framework.random import get_rng_state

    return get_rng_state()


def set_cuda_rng_state(state):
    from ..framework.random import set_rng_state

    return set_rng_state(state)


# ------------------------------------------------------------------ constants
pi = _math.pi
e = _math.e
inf = float("inf")
nan = float("nan")
newaxis = None
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2


# ------------------------------------------------------------- inplace family
def _graft(x: Tensor, out: Tensor) -> Tensor:
    """Install `out`'s value + tape linkage into `x` (paddle inplace
    semantics under the tape: the mutated tensor continues the graph)."""
    x._value = out._value
    x._grad_node = out._grad_node
    x._grad_index = out._grad_index
    x.stop_gradient = out.stop_gradient
    return x


def make_inplace(base_fn, name):
    def inplace(x, *args, **kwargs):
        from ..autograd import tape

        if (not x.stop_gradient and x._grad_node is None
                and tape.is_grad_enabled()):
            # same contract as the reference/torch: the pre-op value of a
            # grad-requiring leaf would be lost for its own backward
            raise RuntimeError(
                f"{name}: a leaf Tensor that requires grad is being used in "
                "an in-place operation")
        # the tape records input OBJECTS: pass a detached alias carrying the
        # ORIGINAL graph linkage so grafting the result onto `x` does not
        # splice the recorded input out of the chain
        alias = Tensor(x._value, stop_gradient=x.stop_gradient)
        alias._grad_node = x._grad_node
        alias._grad_index = x._grad_index
        return _graft(x, base_fn(alias, *args, **kwargs))

    inplace.__name__ = name
    inplace.__doc__ = (f"In-place variant of `{name[:-1]}` (reference "
                       f"tensor API): mutates and returns the input tensor.")
    return inplace
