"""Monkey-patch the op surface onto Tensor as methods + operators.

Reference parity: the reference binds ~400 methods onto the eager Tensor in
python/paddle/tensor/__init__.py (`monkey_patch_tensor`); we do the same so user code
written method-style (`x.sum(1).sqrt()`) works.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import dtype as _dt
from ..tensor import Tensor
from . import (
    creation,
    einsum as _einsum,
    indexing,
    linalg,
    logic,
    manipulation,
    math,
    random as _random,
    reduction,
    search,
)
from . import apply_op

_MODULES = [math, manipulation, logic, reduction, search, linalg, creation]

_SKIP = {"to_tensor", "meshgrid", "broadcast_shape", "assign"}

for mod in _MODULES:
    for name in getattr(mod, "__all__", []):
        if name in _SKIP or hasattr(Tensor, name):
            continue
        fn = getattr(mod, name)
        setattr(Tensor, name, fn)

# explicit extras / renames
Tensor.astype = lambda self, dtype: manipulation.cast(self, dtype)
Tensor.cast = Tensor.astype
Tensor.type_as = lambda self, other: manipulation.cast(self, other.dtype)
Tensor.reshape = manipulation.reshape
Tensor.reshape_ = manipulation.reshape_
Tensor.numel = lambda self: creation.to_tensor(self.size)
Tensor.element_size = lambda self: self.dtype.itemsize
Tensor.rank = lambda self: creation.to_tensor(self.ndim)
Tensor.mm = linalg.mm
Tensor.matmul = linalg.matmul
Tensor.dot = linalg.dot
Tensor.norm = linalg.norm
Tensor.unique = search.unique
Tensor.einsum = lambda self, eq, *others: _einsum.einsum(eq, self, *others)
Tensor.fill_ = lambda self, v: self._replace_(jnp.full_like(self._value, v))
Tensor.zero_ = lambda self: self._replace_(jnp.zeros_like(self._value))
Tensor.uniform_ = _random.uniform_
Tensor.normal_ = _random.normal_
Tensor.exponential_ = _random.exponential_


# in-place arithmetic variants (paddle `add_`, `subtract_`, `scale_`, `clip_`)
def _make_inplace(fname):
    base = getattr(math, fname)

    def inplace(self, *args, **kwargs):
        out = base(self, *args, **kwargs)
        self._value = out._value
        return self

    inplace.__name__ = fname + "_"
    return inplace


for _f in ["add", "subtract", "multiply", "divide", "scale", "clip", "floor", "ceil",
           "round", "sqrt", "rsqrt", "exp", "abs", "tanh", "remainder", "mod", "pow"]:
    setattr(Tensor, _f + "_", _make_inplace(_f))

# ------------------------------------------------------------------ operators
Tensor.__getitem__ = indexing.getitem
Tensor.__setitem__ = indexing.setitem

Tensor.__add__ = lambda s, o: math.add(s, o)
Tensor.__radd__ = lambda s, o: math.add(o if isinstance(o, Tensor) else creation.to_tensor(o, dtype=_rhs_dtype(s, o)), s)
Tensor.__sub__ = lambda s, o: math.subtract(s, o)
Tensor.__rsub__ = lambda s, o: math.subtract(creation.to_tensor(o, dtype=_rhs_dtype(s, o)), s)
Tensor.__mul__ = lambda s, o: math.multiply(s, o)
Tensor.__rmul__ = lambda s, o: math.multiply(creation.to_tensor(o, dtype=_rhs_dtype(s, o)), s)
Tensor.__truediv__ = lambda s, o: math.divide(s, o)
Tensor.__rtruediv__ = lambda s, o: math.divide(creation.to_tensor(o, dtype=_rhs_dtype(s, o)), s)
Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
Tensor.__rfloordiv__ = lambda s, o: math.floor_divide(creation.to_tensor(o), s)
Tensor.__mod__ = lambda s, o: math.mod(s, o)
Tensor.__rmod__ = lambda s, o: math.mod(creation.to_tensor(o, dtype=_rhs_dtype(s, o)), s)
Tensor.__pow__ = lambda s, o: math.pow(s, o)
Tensor.__rpow__ = lambda s, o: math.pow(creation.to_tensor(o, dtype=_rhs_dtype(s, o)), s)
Tensor.__neg__ = lambda s: math.neg(s)
Tensor.__abs__ = lambda s: math.abs(s)
Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
Tensor.__rmatmul__ = lambda s, o: linalg.matmul(creation.to_tensor(o), s)

Tensor.__eq__ = lambda s, o: logic.equal(s, o)
Tensor.__ne__ = lambda s, o: logic.not_equal(s, o)
Tensor.__lt__ = lambda s, o: logic.less_than(s, o)
Tensor.__le__ = lambda s, o: logic.less_equal(s, o)
Tensor.__gt__ = lambda s, o: logic.greater_than(s, o)
Tensor.__ge__ = lambda s, o: logic.greater_equal(s, o)

Tensor.__and__ = lambda s, o: logic.bitwise_and(s, o) if not _is_bool(s) else logic.logical_and(s, o)
Tensor.__or__ = lambda s, o: logic.bitwise_or(s, o) if not _is_bool(s) else logic.logical_or(s, o)
Tensor.__xor__ = lambda s, o: logic.bitwise_xor(s, o) if not _is_bool(s) else logic.logical_xor(s, o)
Tensor.__invert__ = lambda s: logic.bitwise_not(s) if not _is_bool(s) else logic.logical_not(s)
Tensor.__lshift__ = lambda s, o: logic.bitwise_left_shift(s, o)
Tensor.__rshift__ = lambda s, o: logic.bitwise_right_shift(s, o)

# T property
Tensor.T = property(lambda s: manipulation.transpose(s))
Tensor.mT = property(lambda s: manipulation.swapaxes(s, -1, -2))


def _is_bool(t):
    return jnp.issubdtype(t.dtype, jnp.bool_)


def _rhs_dtype(t, o):
    if isinstance(o, float) and jnp.issubdtype(t.dtype, jnp.integer):
        return _dt.get_default_dtype()
    if isinstance(o, (int, float)) and not isinstance(o, bool):
        return t.dtype if not (isinstance(o, float) and jnp.issubdtype(t.dtype, jnp.integer)) else _dt.get_default_dtype()
    return None
