"""Op layer: every paddle op as a pure jax function + tape recording.

Reference parity: replaces the whole YAML→codegen→phi-kernel pipeline
(paddle/phi/ops/yaml/ops.yaml, 470 ops; paddle/phi/kernels/, 2851 registrations in the
reference) with ONE dispatch helper: `apply_op(fn, name, *tensors, **static_kwargs)`.
`fn` is a jax function — XLA supplies every backend's kernel; the tape supplies autograd
via `jax.vjp`; jit tracing works because Tensors wrap tracers transparently.
"""
from __future__ import annotations

import builtins as _builtins
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import tape
from ..tensor import Tensor


def _unwrap(a):
    if isinstance(a, Tensor):
        return a._value
    return a


def _is_diffable(a) -> bool:
    return (
        isinstance(a, Tensor)
        and not a.stop_gradient
        and (
            jnp.issubdtype(a.dtype, jnp.floating)
            or jnp.issubdtype(a.dtype, jnp.complexfloating)
        )
    )


def _amp_wrap(fn, name: str):
    """AMP O1/O2 hook (reference: eager_gen.py emits an AMP branch into every
    ad_func; here ONE dispatch-time wrapper consults the lists). Casting happens
    inside the differentiated fn so astype's VJP casts gradients back to each
    input's original dtype."""
    from .. import amp as _amp

    if not _amp.is_auto_cast_enabled():
        return fn
    level = _amp.get_amp_level()
    target = None
    if name in _amp.black_list():
        target = jnp.float32
    elif level == "O1":
        if name in _amp.white_list():
            target = _amp.get_amp_dtype()
    else:  # O2: everything low-precision except the black list
        target = _amp.get_amp_dtype()
    if target is None:
        return fn

    def amp_fn(*vals, **kwargs):
        cast = [
            v.astype(target)
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)
            and v.dtype != target else v
            for v in vals
        ]
        return fn(*cast, **kwargs)

    return amp_fn


def apply_op(fn, name: str, *args, nout: int | None = None, **kwargs):
    """Run `fn(*vals, **kwargs)`; record a tape node if autograd applies.

    args may be Tensor / jax array / python scalar / None; kwargs are static
    (never differentiated). Returns Tensor or tuple of Tensors (list outputs of fn are
    returned as lists of Tensors, mirroring ops like `split`).
    """
    fn = _amp_wrap(fn, name)
    vals = [_unwrap(a) for a in args]
    need_grad = tape.is_grad_enabled() and _builtins.any(_is_diffable(a) for a in args)

    if not need_grad:
        out = fn(*vals, **kwargs)
        _maybe_scan_nan_inf(name, out)
        _maybe_record_op_stats(name, out)
        return _wrap_outputs(out, stop_gradient=True)

    diff_idx = [i for i, a in enumerate(args) if _is_diffable(a)]

    # ---------------- eager vjp cache (round-1/2 finding: per-op re-trace) ----
    # Keyed on (fn code+cells, name, avals, kwargs): repeat eager steps hit two
    # cached jit programs (fwd; rematerializing bwd) instead of re-tracing
    # jax.vjp on every call. Tracer inputs and unhashable keys use the direct
    # path below.
    in_trace = _builtins.any(isinstance(v, jax.core.Tracer) for v in vals)
    key = None if in_trace else _eager_key(fn, name, vals, tuple(diff_idx), kwargs)
    if key is not None:
        entry = _EAGER_CACHE.get(key)
        if entry is _UNCACHEABLE:
            key = None
        elif entry is not None:
            try:
                return _run_cached(entry, name, args, vals, diff_idx, nout)
            except (jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.TracerIntegerConversionError,
                    jax.errors.TracerBoolConversionError, TypeError):
                # op is value-dependent (an input's VALUES drive output shape,
                # e.g. repeat_interleave with a repeats tensor): jitting it is
                # wrong — blacklist and use the direct path permanently
                _EAGER_CACHE[key] = _UNCACHEABLE
                key = None

    def closure(*diff_vals):
        merged = list(vals)
        for i, v in zip(diff_idx, diff_vals):
            merged[i] = v
        out = fn(*merged, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(out), type(out) is list
        return (out,), False

    primals = [vals[i] for i in diff_idx]
    from ..framework import random as _rng_mod

    rng_before = _rng_mod._consume_count
    out_tuple, vjp_fn, was_list = jax.vjp(closure, *primals, has_aux=True)

    if key is not None:
        if _rng_mod._consume_count != rng_before:
            # the op drew randomness during its trace: a cached jitted program
            # would replay the SAME folded key (identical dropout mask every
            # step) — permanently uncacheable
            _EAGER_CACHE[key] = _UNCACHEABLE
        else:
            _EAGER_CACHE[key] = _build_entry(fn, kwargs, vals, tuple(diff_idx),
                                             was_list)

    _maybe_scan_nan_inf(name, out_tuple)
    _maybe_record_op_stats(name, out_tuple)
    outputs = [Tensor(o, stop_gradient=False) for o in out_tuple]
    tape.record(vjp_fn, [args[i] for i in diff_idx], outputs, name=name)
    if len(outputs) == 1 and not was_list and nout is None:
        return outputs[0]
    return list(outputs) if was_list else tuple(outputs)


_EAGER_CACHE: dict = {}
_UNCACHEABLE = object()
# python scalars stay STATIC (keyed by value): they are frequently structural
# (shape dims, axes); arrays are traced, with the blacklist above as the escape
# hatch for value-dependent ops
_TRACED_TYPES = (jax.Array, np.ndarray, np.generic)


def _cell_key(v, depth=0):
    """Hashable stand-in for one closure cell value (None = give up)."""
    if isinstance(v, (jax.Array, np.ndarray)):
        return None  # data in a closure: unsafe to key on
    if callable(v) and hasattr(v, "__code__") and depth < 2:
        if getattr(v, "__self__", None) is not None:
            return None  # bound method in a cell: instance state invisible
        inner = tuple(
            _cell_key(c.cell_contents, depth + 1) for c in (v.__closure__ or ())
        )
        if _builtins.any(c is None for c in inner):
            return None
        if v.__defaults__:
            dflt = tuple(_cell_key(d, depth + 1) for d in v.__defaults__)
            if _builtins.any(d is None for d in dflt):
                return None
            inner = inner + (("__defaults__",) + dflt,)
        return (v.__code__, inner)
    try:
        hash(v)
        return (type(v).__name__, v)
    except TypeError:
        if isinstance(v, (list, tuple)):
            parts = tuple(_cell_key(e, depth + 1) for e in v)
            return None if _builtins.any(p is None for p in parts) else parts
        return None


def _eager_key(fn, name, vals, diff_idx, kwargs):
    if getattr(fn, "__self__", None) is not None:
        # bound method: behavior depends on instance state that the key cannot
        # see (e.g. a Transform's sub-transform list) — two instances of the
        # same class would collide on one cache entry. Never cache.
        return None
    code = getattr(fn, "__code__", None)
    if code is None:
        # builtin / PjitFunction: key on the object itself (the cache entry
        # keeps it alive, so identity is stable)
        try:
            hash(fn)
        except TypeError:
            return None
        code, cells = fn, ()
    else:
        cells = tuple(_cell_key(c.cell_contents) for c in (fn.__closure__ or ()))
        if _builtins.any(c is None for c in cells):
            return None
        if fn.__defaults__:
            # default args parameterize behavior (e.g. lambda v, n=2: ...)
            dflt = tuple(_cell_key(d) for d in fn.__defaults__)
            if _builtins.any(d is None for d in dflt):
                return None
            cells = cells + (("__defaults__",) + dflt,)
    sig = []
    for v in vals:
        if isinstance(v, (jax.Array, np.ndarray, np.generic)):
            sig.append(("a", tuple(v.shape), str(v.dtype)))
        elif type(v) in (float, int, bool, complex):
            sig.append(("s", type(v).__name__, v))  # static: keyed by value
        else:
            k = _cell_key(v)
            if k is None:
                return None
            sig.append(("c", k))
    try:
        kw = tuple(sorted((k, _cell_key(v)) for k, v in kwargs.items()))
    except TypeError:
        return None
    if _builtins.any(v is None for _, v in kw):
        return None
    return (code, cells, name, tuple(sig), kw, diff_idx)


def _build_entry(fn, kwargs, vals, diff_idx, was_list):
    """Jitted fwd + rematerializing bwd specialized to this call signature."""
    n = len(vals)
    traced_pos = tuple(i for i, v in enumerate(vals)
                       if isinstance(v, _TRACED_TYPES))
    static_by_pos = {i: vals[i] for i in range(n) if i not in traced_pos}
    diff_slots = tuple(traced_pos.index(i) for i in diff_idx)

    def primal(traced_vals):
        merged = []
        ti = 0
        for i in range(n):
            if i in static_by_pos:
                merged.append(static_by_pos[i])
            else:
                merged.append(traced_vals[ti])
                ti += 1
        out = fn(*merged, **kwargs)
        return tuple(out) if isinstance(out, (tuple, list)) else (out,)

    @jax.jit
    def fwd(traced_vals):
        return primal(traced_vals)

    @jax.jit
    def bwd(ct, traced_vals):
        def diff_closure(*diff_vals):
            tv = list(traced_vals)
            for slot, v in zip(diff_slots, diff_vals):
                tv[slot] = v
            return primal(tv)

        _, vjp_fn = jax.vjp(diff_closure,
                            *[traced_vals[s] for s in diff_slots])
        return vjp_fn(ct)

    return (fwd, bwd, was_list, traced_pos)


def _run_cached(entry, name, args, vals, diff_idx, nout):
    fwd, bwd, was_list, traced_pos = entry
    traced_vals = tuple(vals[i] for i in traced_pos)
    out_tuple = fwd(traced_vals)
    _maybe_scan_nan_inf(name, out_tuple)
    _maybe_record_op_stats(name, out_tuple)
    outputs = [Tensor(o, stop_gradient=False) for o in out_tuple]
    tape.record(lambda ct: bwd(ct, traced_vals),
                [args[i] for i in diff_idx], outputs, name=name)
    if len(outputs) == 1 and not was_list and nout is None:
        return outputs[0]
    return list(outputs) if was_list else tuple(outputs)


_dbg_mod = None


def _maybe_record_op_stats(name, out):
    """amp.debugging operator-stats hook (near-zero cost when collection is
    off: one global load + None check; module bound once on first use)."""
    global _dbg_mod
    if _dbg_mod is None:
        from ..amp import debugging as _dbg

        _dbg_mod = _dbg
    if _dbg_mod._stats is not None:
        _dbg_mod._record_op(name, out)


def _maybe_scan_nan_inf(name, out):
    """Per-op NaN/Inf scan (reference: FLAGS_check_nan_inf in
    paddle/fluid/framework/details/nan_inf_utils; flags.cc). Eager-only: traced
    values are skipped (the compiled path uses amp.check_numerics)."""
    from ..framework.flags import flag

    if not flag("FLAGS_check_nan_inf"):
        return
    leaves = out if isinstance(out, (tuple, list)) else [out]
    for i, v in enumerate(leaves):
        if isinstance(v, jax.core.Tracer) or not hasattr(v, "dtype"):
            continue
        if not jnp.issubdtype(v.dtype, jnp.floating):
            continue
        bad = int(jnp.sum(~jnp.isfinite(v)))
        if bad:
            msg = f"op {name!r} output {i} contains {bad} NaN/Inf values"
            if flag("FLAGS_check_nan_inf_level") >= 1:
                import warnings

                warnings.warn(msg)
            else:
                raise FloatingPointError(msg)


def _wrap_outputs(out, stop_gradient=True):
    if isinstance(out, tuple):
        return tuple(Tensor(o, stop_gradient=stop_gradient) for o in out)
    if isinstance(out, list):
        return [Tensor(o, stop_gradient=stop_gradient) for o in out]
    return Tensor(out, stop_gradient=stop_gradient)


def unary_op(jfn, name):
    def op(x, name=None):
        return apply_op(jfn, name or op.__name__, x)

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"paddle.{name} — elementwise, lowered to jnp.{getattr(jfn, '__name__', name)}."
    return op


def binary_op(jfn, name):
    def op(x, y, name=None):
        return apply_op(jfn, op.__name__, x, y)

    op.__name__ = name
    op.__qualname__ = name
    return op


# Submodules (import order matters: creation/math monkey-patch Tensor methods).
from . import creation  # noqa: E402
from . import math  # noqa: E402
from . import manipulation  # noqa: E402
from . import logic  # noqa: E402
from . import reduction  # noqa: E402
from . import search  # noqa: E402
from . import linalg  # noqa: E402
from . import random  # noqa: E402
from . import indexing  # noqa: E402
from . import einsum as _einsum_mod  # noqa: E402

from .creation import *  # noqa: F401,F403,E402
from .math import *  # noqa: F401,F403,E402
from .manipulation import *  # noqa: F401,F403,E402
from .logic import *  # noqa: F401,F403,E402
from .reduction import *  # noqa: F401,F403,E402
from .search import *  # noqa: F401,F403,E402
from .linalg import *  # noqa: F401,F403,E402
from .random import *  # noqa: F401,F403,E402
from .einsum import einsum  # noqa: F401,E402
from .parity import *  # noqa: F401,F403,E402
from . import parity as _parity  # noqa: E402

# in-place variants: <op>_ mutates the tensor, keeping tape linkage
_INPLACE_BASES = [
    "abs", "acos", "addmm", "atan", "bernoulli", "bitwise_and",
    "bitwise_left_shift", "bitwise_not", "bitwise_or", "bitwise_right_shift",
    "bitwise_xor", "cast", "copysign", "cos", "cumprod", "cumsum", "digamma",
    "divide", "equal", "erf", "expm1", "floor_divide", "frac", "gcd",
    "greater_equal", "greater_than", "hypot", "i0", "index_add", "index_put",
    "lcm", "ldexp", "less_equal", "less_than", "lgamma", "log10", "log2",
    "log", "log_normal", "logical_and", "logical_not", "logical_or", "logit",
    "masked_fill", "masked_scatter", "mod", "multiply", "nan_to_num", "neg",
    "pow", "remainder", "scatter", "sin", "sinh", "square", "t", "tan",
    "tanh", "transpose", "tril", "triu", "trunc", "where", "gammaln",
    "gammainc", "gammaincc", "multigammaln", "polygamma", "renorm", "sinc",
    "floor_mod", "less",
]
# aliases the reference exports under second names
bitwise_invert = globals().get("bitwise_not")
for _base in _INPLACE_BASES:
    _fn = globals().get(_base)
    if _fn is not None and callable(_fn):
        globals()[_base + "_"] = _parity.make_inplace(_fn, _base + "_")
bitwise_invert_ = globals().get("bitwise_not_")
less_ = globals().get("less_than_")
floor_mod_ = globals().get("mod_")

# bind every generated in-place variant (and add_/sub_ method aliases) onto
# Tensor, mirroring the reference's monkey_patch_tensor inplace set
from ..tensor import Tensor as _T  # noqa: E402

for _n, _f in list(globals().items()):
    if _n.endswith("_") and not _n.startswith("_") and callable(_f) \
            and not hasattr(_T, _n):
        setattr(_T, _n, _f)
if not hasattr(_T, "add_"):
    _T.add_ = _parity.make_inplace(globals()["add"], "add_")
if not hasattr(_T, "subtract_"):
    _T.subtract_ = _parity.make_inplace(globals()["subtract"], "subtract_")


from . import patch_methods  # noqa: E402  (binds Tensor methods/operators)
