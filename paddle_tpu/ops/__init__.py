"""Op layer: every paddle op as a pure jax function + tape recording.

Reference parity: replaces the whole YAML→codegen→phi-kernel pipeline
(paddle/phi/ops/yaml/ops.yaml, 470 ops; paddle/phi/kernels/, 2851 registrations in the
reference) with ONE dispatch helper: `apply_op(fn, name, *tensors, **static_kwargs)`.
`fn` is a jax function — XLA supplies every backend's kernel; the tape supplies autograd
via `jax.vjp`; jit tracing works because Tensors wrap tracers transparently.
"""
from __future__ import annotations

import builtins as _builtins
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import tape
from ..tensor import Tensor


def _unwrap(a):
    if isinstance(a, Tensor):
        return a._value
    return a


def _is_diffable(a) -> bool:
    return (
        isinstance(a, Tensor)
        and not a.stop_gradient
        and (
            jnp.issubdtype(a.dtype, jnp.floating)
            or jnp.issubdtype(a.dtype, jnp.complexfloating)
        )
    )


def _amp_wrap(fn, name: str):
    """AMP O1/O2 hook (reference: eager_gen.py emits an AMP branch into every
    ad_func; here ONE dispatch-time wrapper consults the lists). Casting happens
    inside the differentiated fn so astype's VJP casts gradients back to each
    input's original dtype."""
    from .. import amp as _amp

    if not _amp.is_auto_cast_enabled():
        return fn
    level = _amp.get_amp_level()
    target = None
    if name in _amp.black_list():
        target = jnp.float32
    elif level == "O1":
        if name in _amp.white_list():
            target = _amp.get_amp_dtype()
    else:  # O2: everything low-precision except the black list
        target = _amp.get_amp_dtype()
    if target is None:
        return fn

    def amp_fn(*vals, **kwargs):
        cast = [
            v.astype(target)
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)
            and v.dtype != target else v
            for v in vals
        ]
        return fn(*cast, **kwargs)

    return amp_fn


def apply_op(fn, name: str, *args, nout: int | None = None, **kwargs):
    """Run `fn(*vals, **kwargs)`; record a tape node if autograd applies.

    args may be Tensor / jax array / python scalar / None; kwargs are static
    (never differentiated). Returns Tensor or tuple of Tensors (list outputs of fn are
    returned as lists of Tensors, mirroring ops like `split`).
    """
    fn = _amp_wrap(fn, name)
    vals = [_unwrap(a) for a in args]
    need_grad = tape.is_grad_enabled() and _builtins.any(_is_diffable(a) for a in args)

    if not need_grad:
        out = fn(*vals, **kwargs)
        return _wrap_outputs(out, stop_gradient=True)

    diff_idx = [i for i, a in enumerate(args) if _is_diffable(a)]

    def closure(*diff_vals):
        merged = list(vals)
        for i, v in zip(diff_idx, diff_vals):
            merged[i] = v
        out = fn(*merged, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(out), type(out) is list
        return (out,), False

    primals = [vals[i] for i in diff_idx]
    out_tuple, vjp_fn, was_list = jax.vjp(closure, *primals, has_aux=True)

    outputs = [Tensor(o, stop_gradient=False) for o in out_tuple]
    tape.record(vjp_fn, [args[i] for i in diff_idx], outputs, name=name)
    if len(outputs) == 1 and not was_list and nout is None:
        return outputs[0]
    return list(outputs) if was_list else tuple(outputs)


def _wrap_outputs(out, stop_gradient=True):
    if isinstance(out, tuple):
        return tuple(Tensor(o, stop_gradient=stop_gradient) for o in out)
    if isinstance(out, list):
        return [Tensor(o, stop_gradient=stop_gradient) for o in out]
    return Tensor(out, stop_gradient=stop_gradient)


def unary_op(jfn, name):
    def op(x, name=None):
        return apply_op(jfn, name or op.__name__, x)

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"paddle.{name} — elementwise, lowered to jnp.{getattr(jfn, '__name__', name)}."
    return op


def binary_op(jfn, name):
    def op(x, y, name=None):
        return apply_op(jfn, op.__name__, x, y)

    op.__name__ = name
    op.__qualname__ = name
    return op


# Submodules (import order matters: creation/math monkey-patch Tensor methods).
from . import creation  # noqa: E402
from . import math  # noqa: E402
from . import manipulation  # noqa: E402
from . import logic  # noqa: E402
from . import reduction  # noqa: E402
from . import search  # noqa: E402
from . import linalg  # noqa: E402
from . import random  # noqa: E402
from . import indexing  # noqa: E402
from . import einsum as _einsum_mod  # noqa: E402

from .creation import *  # noqa: F401,F403,E402
from .math import *  # noqa: F401,F403,E402
from .manipulation import *  # noqa: F401,F403,E402
from .logic import *  # noqa: F401,F403,E402
from .reduction import *  # noqa: F401,F403,E402
from .search import *  # noqa: F401,F403,E402
from .linalg import *  # noqa: F401,F403,E402
from .random import *  # noqa: F401,F403,E402
from .einsum import einsum  # noqa: F401,E402

from . import patch_methods  # noqa: E402  (binds Tensor methods/operators)
