"""Search/sort ops. Reference: python/paddle/tensor/search.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..tensor import Tensor
from . import apply_op

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero", "searchsorted",
    "bucketize", "index_of_max", "unique", "unique_consecutive",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(
        lambda v: jnp.argmax(v, axis=axis, keepdims=keepdim if axis is not None else False).astype(d),
        "argmax", x,
    )


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(
        lambda v: jnp.argmin(v, axis=axis, keepdims=keepdim if axis is not None else False).astype(d),
        "argmin", x,
    )


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(v):
        idx = jnp.argsort(v, axis=axis, stable=True, descending=descending)
        return idx.astype(_dt.int64)

    return apply_op(f, "argsort", x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(v):
        out = jnp.sort(v, axis=axis, stable=True, descending=descending)
        return out

    return apply_op(f, "sort", x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)

    def f(v):
        ax = v.ndim - 1 if axis is None else axis % v.ndim
        vv = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = _topk_last(vv, kk)
        else:
            nvals, idx = _topk_last(-vv, kk)
            vals = -nvals
        return (
            jnp.moveaxis(vals, -1, ax),
            jnp.moveaxis(idx.astype(_dt.int64), -1, ax),
        )

    return apply_op(f, "topk", x)


def _topk_last(v, k):
    import jax

    return jax.lax.top_k(v, k)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)

    def f(c, a, b):
        if a.dtype != b.dtype:
            rd = jnp.result_type(a, b)
            a, b = a.astype(rd), b.astype(rd)
        return jnp.where(c, a, b)

    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))
    return apply_op(f, "where", condition, xt, yt)


def nonzero(x, as_tuple=False):
    # data-dependent shape → host computation (documented dynamic boundary)
    v = np.asarray(x._value)
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.reshape(-1, 1), dtype=_dt.int64)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), dtype=_dt.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = _dt.int32 if out_int32 else _dt.int64

    def f(s, v):
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side).astype(d)
        import jax

        return jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(
            s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape).astype(d)

    return apply_op(f, "searchsorted", sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_of_max(x, axis=None):
    return argmax(x, axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    v = np.asarray(x._value)
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    d = _dt.convert_dtype(dtype)
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(res[0]))]
    for extra in res[1:]:
        out.append(Tensor(jnp.asarray(extra.astype(d))))
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    v = np.asarray(x._value)
    if axis is None:
        v = v.reshape(-1)
        ax = 0
    else:
        ax = axis
    moved = np.moveaxis(v, ax, 0)
    keep = np.ones(moved.shape[0], bool)
    if moved.shape[0] > 1:
        eq = (moved[1:] == moved[:-1]).reshape(moved.shape[0] - 1, -1).all(axis=1)
        keep[1:] = ~eq
    uniq = np.moveaxis(moved[keep], 0, ax)
    outs = [Tensor(jnp.asarray(uniq))]
    d = _dt.convert_dtype(dtype)
    if return_inverse:
        grp = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(grp.astype(d))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, moved.shape[0]))
        outs.append(Tensor(jnp.asarray(counts.astype(d))))
    return outs[0] if len(outs) == 1 else tuple(outs)
