"""Reduction ops. Reference: python/paddle/tensor/math.py (sum/mean/...) & stat.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..tensor import Tensor
from . import apply_op

__all__ = [
    "sum", "mean", "max", "min", "prod", "amax", "amin", "all", "any", "std", "var",
    "median", "nanmedian", "nansum", "nanmean", "quantile", "nanquantile", "logsumexp",
    "mode", "kthvalue",
]


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = np.asarray(axis._value)
        return tuple(int(v) for v in a.reshape(-1)) if a.ndim else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    d = _dt.convert_dtype(dtype)

    def f(v):
        out = jnp.sum(v, axis=ax, keepdims=keepdim, dtype=d)
        if d is None and jnp.issubdtype(v.dtype, jnp.bool_):
            out = out.astype(_dt.int64)
        return out

    return apply_op(f, "sum", x)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(lambda v: jnp.mean(v, axis=ax, keepdims=keepdim), "mean", x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _norm_axis(axis)
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda v: jnp.prod(v, axis=ax, keepdims=keepdim, dtype=d), "prod", x)


def max(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(lambda v: jnp.max(v, axis=ax, keepdims=keepdim), "max", x)


def min(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(lambda v: jnp.min(v, axis=ax, keepdims=keepdim), "min", x)


amax = max
amin = min


def all(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(lambda v: jnp.all(v, axis=ax, keepdims=keepdim), "all", x)


def any(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(lambda v: jnp.any(v, axis=ax, keepdims=keepdim), "any", x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(
        lambda v: jnp.std(v, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim), "std", x
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(
        lambda v: jnp.var(v, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim), "var", x
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _norm_axis(axis)

    def f(v):
        if mode == "avg":
            return jnp.median(v, axis=ax, keepdims=keepdim)
        # 'min' mode: lower of the two middle values + its index (paddle returns both)
        vv = v.reshape(-1) if ax is None else v
        a = 0 if ax is None else ax
        n = vv.shape[a]
        k = (n - 1) // 2
        sorted_v = jnp.sort(vv, axis=a)
        sorted_i = jnp.argsort(vv, axis=a)
        vals = jnp.take(sorted_v, jnp.asarray([k]), axis=a)
        idxs = jnp.take(sorted_i, jnp.asarray([k]), axis=a)
        if not keepdim:
            vals = jnp.squeeze(vals, axis=a)
            idxs = jnp.squeeze(idxs, axis=a)
        return vals, idxs.astype(_dt.int64)

    return apply_op(f, "median", x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _norm_axis(axis)
    return apply_op(lambda v: jnp.nanmedian(v, axis=ax, keepdims=keepdim), "nanmedian", x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda v: jnp.nansum(v, axis=ax, keepdims=keepdim, dtype=d), "nansum", x)


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(lambda v: jnp.nanmean(v, axis=ax, keepdims=keepdim), "nanmean", x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _norm_axis(axis)
    qv = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op(
        lambda v: jnp.quantile(
            v.astype(jnp.float64) if v.dtype == jnp.float64 else v.astype(jnp.float32),
            qv, axis=ax, keepdims=keepdim, method=interpolation
        ),
        "quantile", x,
    )


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _norm_axis(axis)
    qv = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op(
        lambda v: jnp.nanquantile(v.astype(jnp.float32), qv, axis=ax, keepdims=keepdim,
                                  method=interpolation),
        "nanquantile", x,
    )


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(
        lambda v: jax.scipy.special.logsumexp(v, axis=ax, keepdims=keepdim), "logsumexp", x
    )


def mode(x, axis=-1, keepdim=False, name=None):
    def f(v):
        vals = jnp.sort(v, axis=axis)
        idxs = jnp.argsort(v, axis=axis)
        # mode = most frequent; for floats paddle picks largest on tie. Simple approach:
        # compare each sorted element with neighbors to get run lengths via cumsum trick.
        moved = jnp.moveaxis(vals, axis, -1)
        n = moved.shape[-1]
        same = jnp.concatenate(
            [jnp.zeros(moved.shape[:-1] + (1,), bool), moved[..., 1:] == moved[..., :-1]],
            axis=-1,
        )
        run_id = jnp.cumsum(~same, axis=-1)
        counts = jax.nn.one_hot(run_id, n + 1, dtype=jnp.int32).sum(-2)
        run_len = jnp.take_along_axis(counts, run_id, axis=-1)
        best = jnp.argmax(run_len, axis=-1)  # last max wins → largest value on tie
        best = (n - 1) - jnp.argmax(jnp.flip(run_len, -1), axis=-1)
        mode_vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
        midx = jnp.moveaxis(idxs, axis, -1)
        mode_idx = jnp.take_along_axis(midx, best[..., None], axis=-1)[..., 0]
        if keepdim:
            mode_vals = jnp.expand_dims(mode_vals, axis)
            mode_idx = jnp.expand_dims(mode_idx, axis)
        return mode_vals, mode_idx.astype(_dt.int64)

    return apply_op(f, "mode", x)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(v):
        vals = jnp.sort(v, axis=axis)
        idxs = jnp.argsort(v, axis=axis)
        sel = jnp.take(vals, jnp.asarray([k - 1]), axis=axis)
        seli = jnp.take(idxs, jnp.asarray([k - 1]), axis=axis)
        if not keepdim:
            sel = jnp.squeeze(sel, axis)
            seli = jnp.squeeze(seli, axis)
        return sel, seli.astype(_dt.int64)

    return apply_op(f, "kthvalue", x)
