"""__getitem__/__setitem__ semantics. Reference: python/paddle/base/variable_index.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor
from . import apply_op


def _convert_index(item):
    """Normalize a paddle index into a jax-compatible index. Returns (index, dynamic)
    where dynamic=True means data-dependent output shape (bool mask)."""
    if isinstance(item, tuple):
        converted = tuple(_convert_one(i) for i in item)
        dynamic = any(d for _, d in converted)
        return tuple(c for c, _ in converted), dynamic
    c, d = _convert_one(item)
    return c, d


def _convert_one(i):
    if isinstance(i, Tensor):
        if jnp.issubdtype(i.dtype, jnp.bool_):
            return np.asarray(i._value), True
        return i._value.astype(jnp.int32), False
    if isinstance(i, np.ndarray) and i.dtype == bool:
        return i, True
    if isinstance(i, (list, np.ndarray)):
        arr = np.asarray(i)
        if arr.dtype == bool:
            return arr, True
        return jnp.asarray(arr, jnp.int32), False
    return i, False  # int / slice / None / Ellipsis


def getitem(x, item):
    idx, dynamic = _convert_index(item)
    if dynamic:
        # bool-mask select: data-dependent shape → host gather (outside jit only)
        v = np.asarray(x._value)
        return Tensor(jnp.asarray(v[_host_index(item)]))
    return apply_op(lambda v: v[idx], "getitem", x)


def _host_index(item):
    if isinstance(item, tuple):
        return tuple(_host_one(i) for i in item)
    return _host_one(item)


def _host_one(i):
    if isinstance(i, Tensor):
        return np.asarray(i._value)
    if isinstance(i, (list, np.ndarray)):
        return np.asarray(i)
    return i


def setitem(x, item, value):
    """In-place semantics via functional .at[] update; rebinding the payload."""
    val = value._value if isinstance(value, Tensor) else value
    if isinstance(val, (int, float, bool)):
        val = jnp.asarray(val, x._value.dtype)
    elif not isinstance(val, jnp.ndarray):
        val = jnp.asarray(np.asarray(val), x._value.dtype)
    else:
        val = val.astype(x._value.dtype)
    idx, dynamic = _convert_index(item)
    if dynamic:
        v = np.asarray(x._value).copy()
        v[_host_index(item)] = np.asarray(val)
        x._value = jnp.asarray(v)
        return x
    x._value = x._value.at[idx].set(val)
    return x
